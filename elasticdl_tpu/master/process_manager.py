"""Elastic worker process manager (local-process instance manager).

Reference parity: elasticdl/python/master/k8s_instance_manager.py — create
worker instances, watch their lifecycle, relaunch failures up to
`relaunch_max`, and tell the membership/dispatcher when one dies. This is the
same state machine with subprocesses instead of pods (the k8s flavor renders
pod specs through client/k8s.py); the master's control plane is identical in
both, which is what makes the fault-injection tests honest — they kill real
worker processes, as the reference's integration tests killed real pods.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from elasticdl_tpu.common import faults, membership_signal
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.net import free_port
from elasticdl_tpu.common.constants import ExitCode, PodStatus, WorkerEnv
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.membership import Membership
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_REFORMS = _reg.counter(
    "edl_reform_total", "cohort re-formations", labels=("kind",))
_REFORM_S = _reg.histogram(
    "edl_reform_seconds", "respawn wall time of a re-formation")
_SPAWNS = _reg.counter(
    "edl_reform_worker_spawns_total", "worker processes spawned")
_COHORT_SIZE = _reg.gauge(
    "edl_reform_cohort_size", "current cohort process count")


def _reject_plain_training_scale_out(cfg: JobConfig) -> None:
    """Runtime twin of JobConfig.validate's multi-replica rule: growing a
    TRAINING job beyond one plain (non-cohort) worker would train divergent
    replicas with no gradient exchange — the config guard must not be
    bypassable through the scale-out API."""
    from elasticdl_tpu.common.constants import JobType

    if cfg.job_type in (JobType.TRAINING_ONLY, JobType.TRAINING_WITH_EVALUATION):
        raise RuntimeError(
            "add_worker on a training job with plain workers would create "
            "independent model replicas (no gradient exchange); use the SPMD "
            "cohort (num_processes>1), whose add_worker re-forms the world"
        )


@dataclass
class _WorkerProc:
    worker_id: int
    proc: subprocess.Popen
    relaunches: int = 0
    status: str = PodStatus.RUNNING
    # cohort mode: this member is permanently gone (host lost, eviction) —
    # its death must trigger a downsized re-formation, not an in-place
    # relaunch that would just die again
    no_relaunch: bool = False
    # deliberately evicted by policy (master/autoscaler.py): its exit is
    # an expected retirement (status DELETED), never a failure that
    # counts toward all_failed() or burns a relaunch
    evicted: bool = False


class ProcessManager:
    """Spawns and babysits worker subprocesses."""

    def __init__(
        self,
        cfg: JobConfig,
        membership: Optional[Membership] = None,
        extra_env: Optional[Dict[str, str]] = None,
        log_dir: Optional[str] = None,
        job_finished_fn=None,
        checkpoint_request_fn=None,
        resize_checkpoint_timeout_s: float = 30.0,
        membership_signal_path: Optional[str] = None,
        journal=None,
    ):
        self.cfg = cfg
        self._membership = membership
        # Crash durability (master/journal.py): world-version bumps are
        # journaled so a restarted master's manager continues the version
        # sequence instead of rewinding it (workers compare versions to
        # decide whether a rescale announcement is news). None = volatile.
        self._journal = journal                      # guarded_by: _lock
        self._extra_env = dict(extra_env or {})
        self._log_dir = log_dir
        # when this returns True, worker exits are final — no relaunches
        self._job_finished_fn = job_finished_fn or (lambda: False)
        # Deliberate-resize quiesce: called before tearing a healthy cohort
        # down so workers checkpoint at the next task boundary (wired to
        # servicer.request_checkpoint by the launcher); the teardown then
        # waits up to resize_checkpoint_timeout_s for a NEW checkpoint to
        # land, bounding the work a planned resize throws away to one task.
        self._checkpoint_request_fn = checkpoint_request_fn
        self._resize_ckpt_timeout_s = resize_checkpoint_timeout_s
        self._probe_ckpt_mngr = None  # lazily built, reused across resizes
        self._procs: Dict[int, _WorkerProc] = {}     # guarded_by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._next_worker_id = 0                     # guarded_by: _lock
        self._cohort_relaunches = 0                  # guarded_by: _lock
        self._cohort_coordinator = ""                # guarded_by: _lock
        # dynamic world resizing state (cohort mode); a replayed journal
        # resumes the pre-crash world version so the next reform bumps
        # PAST it (never backwards past what workers already saw)
        self._cohort_size = self.cfg.num_processes   # guarded_by: _lock
        self._world_version = (                      # guarded_by: _lock
            journal.world_version if journal is not None else 0
        )
        self._pending_resize: Optional[int] = None   # guarded_by: _lock
        self._infra_retries = 0                      # guarded_by: _lock
        # world-formation failures (coordinator-port TOCTOU etc.) retry
        # without consuming the relaunch budget, bounded by this cap
        self.infra_retry_max = 10
        # timestamped re-formation records: (wall_clock_s, old_size, new_size)
        self.reformation_log: List[Tuple[float, int, int]] = []  # guarded_by: _lock
        # Pending-membership signal (rescale fast path): a planned resize is
        # ANNOUNCED through this file before the teardown lands, so workers'
        # speculative compilers precompile the next world size while the old
        # one still trains. Default location: the log dir (shared with the
        # workers on this manager's single host); "" disables.
        if membership_signal_path is None:
            base = log_dir or self.cfg.checkpoint_dir
            membership_signal_path = (
                os.path.join(base, "membership_signal.json") if base else ""
            )
        self._signal_path = membership_signal_path
        if self._signal_path and journal is not None and journal.recovered:
            # full master-process restart: the signal file at THIS path
            # (log_dir-based — Master.__init__'s own takeover clear only
            # knows checkpoint_dir, which differs whenever log_dir is set)
            # may still carry the dead predecessor's announced resize plan;
            # drop it before any worker's speculative compiler reads it
            membership_signal.clear_stale_on_takeover(
                self._signal_path, master_generation=journal.generation
            )
        # one trace id per announced/active resize: stamped into the signal
        # file (workers adopt it) and onto every reform.* span this manager
        # opens, so master + workers share a timeline per resize
        self._reform_trace_id: Optional[str] = None   # guarded_by: _lock
        # observer for measured re-formation durations (the autoscaler's
        # cost model subscribes — client/local.py wires it); best-effort,
        # called OUTSIDE the lock with (seconds, old_size, new_size)
        self._reform_observers: List = []

    @property
    def _cohort_mode(self) -> bool:
        return self.cfg.num_processes > 1

    @property
    def cohort_size(self) -> int:
        with self._lock:
            return self._cohort_size

    def pending_size(self) -> Optional[int]:
        """The announced (not yet applied) next cohort size, if any."""
        with self._lock:
            return self._pending_resize

    def _announce_locked(self) -> None:  # holds: _lock
        """(Re)write the pending-membership signal file from the current
        locked state. Best-effort — the announcement is an optimization
        for the workers' speculative compilers, never a failure source."""
        if not self._signal_path:
            return
        membership_signal.write_signal(
            self._signal_path,
            world_size=self._cohort_size,
            pending_size=self._pending_resize,
            world_version=self._world_version,
            trace_id=self._reform_trace_id,
            # which master wrote this plan: a successor master at takeover
            # clears announcements stamped by its dead predecessor
            master_generation=(
                self._journal.generation if self._journal is not None else 0
            ),
        )

    def rebind_master(
        self, membership, job_finished_fn, checkpoint_request_fn, journal=None
    ) -> None:
        """Adopt a RESTARTED in-process master (client/local.py's
        --master_restarts recovery path): swap the control-plane hooks to
        the successor's membership/dispatcher/servicer and its replayed
        journal. The worker processes themselves are untouched — they
        reconnect to the same address under the new generation; only this
        manager's references move. The announcement is re-stamped so the
        signal file carries the new master generation immediately."""
        with self._lock:
            self._membership = membership
            self._job_finished_fn = job_finished_fn or (lambda: False)
            self._checkpoint_request_fn = checkpoint_request_fn
            self._journal = journal
            self._announce_locked()
        logger.warning(
            "process manager rebound to restarted master (generation %d)",
            journal.generation if journal is not None else 0,
        )


    # ------------------------------------------------------------------ #

    def _spawn(self, worker_id: int, relaunches: int = 0,  # holds: _lock
               process_id: int = 0) -> _WorkerProc:
        # called with the lock held: the cohort env block reads
        # _cohort_coordinator/_cohort_size/_world_version
        env = dict(os.environ)
        env.update({str(k): str(v) for k, v in self.cfg.envs.items()})
        env.update(self._extra_env)
        env[WorkerEnv.WORKER_ID] = str(worker_id)
        env[WorkerEnv.MASTER_ADDR] = self.cfg.master_addr
        env[WorkerEnv.NUM_WORKERS] = str(self.cfg.num_workers)
        if self._cohort_mode:
            env["EDL_PROCESS_ID"] = str(process_id)
            env["EDL_COORDINATOR_ADDR"] = self._cohort_coordinator
            # dynamic resizing: the CURRENT world size/generation, which may
            # differ from the argv's immutable cfg.num_processes
            env["EDL_NUM_PROCESSES"] = str(self._cohort_size)
            env["EDL_WORLD_VERSION"] = str(self._world_version)
        if self._signal_path:
            # where workers read the pending-membership announcement
            env[membership_signal.ENV_VAR] = self._signal_path
        argv = self.cfg.to_argv()
        stdout = stderr = None
        if self._log_dir:
            os.makedirs(self._log_dir, exist_ok=True)
            name = (
                f"worker-{worker_id}-p{process_id}.log"
                if self._cohort_mode else f"worker-{worker_id}.log"
            )
            # spawn-under-lock is the cohort-atomicity invariant: the proc
            # table, cohort size, and coordinator port must not be observed
            # mid-reform, and spawn is the repair path, not the hot path:
            # edl-lint: disable=EDL103
            log = open(os.path.join(self._log_dir, name), "ab")
            stdout = stderr = log
        cmd = [sys.executable, "-m", "elasticdl_tpu.worker.main", *argv]
        try:
            # chaos hook: delay/crash keep their documented semantics
            # (crash = os._exit of THIS process, honoring code=); drop is
            # remapped below
            faults.fire("proc.spawn")
        except faults.FaultInjected:
            # drop: spawn a doomed stand-in that exits 1 immediately (a pod
            # that never comes up), exercising death detection and the
            # relaunch budget rather than silently skipping the spawn
            cmd = [sys.executable, "-c", "raise SystemExit(1)"]
        # same cohort-atomicity justification as the log open above:
        # edl-lint: disable=EDL103
        proc = subprocess.Popen(
            cmd,
            env=env,
            stdout=stdout,
            stderr=stderr,
        )
        wp = _WorkerProc(worker_id=worker_id, proc=proc, relaunches=relaunches)
        _SPAWNS.inc()
        logger.info("spawned worker %d (pid %d)", worker_id, proc.pid)
        return wp

    def start_workers(self) -> None:
        with self._lock:
            # fresh job, fresh announcement: a stale pending_size left by a
            # crashed previous run (same log dir) must not send the new
            # workers' speculative compilers chasing a phantom resize
            self._announce_locked()
            if self._cohort_mode:
                self._spawn_cohort_locked()
            else:
                for _ in range(self.cfg.num_workers):
                    wid = self._next_worker_id
                    self._next_worker_id += 1
                    self._procs[wid] = self._spawn(wid)
        self._watcher = threading.Thread(target=self._watch_loop, daemon=True)
        self._watcher.start()

    def _spawn_cohort_locked(self, size: Optional[int] = None) -> None:
        """Spawn all cohort members (process id == slot id; the leader,
        process 0, registers with the master as worker 0). A fresh
        coordinator port per generation avoids TIME_WAIT rebind races;
        a bind lost to the TOCTOU window surfaces as ExitCode.WORLD_FORM_FAILED
        and is retried budget-free by the watch loop."""
        if size is not None:
            self._cohort_size = size
        self._cohort_coordinator = f"localhost:{free_port()}"
        for p in range(self._cohort_size):
            self._procs[p] = self._spawn(
                0, relaunches=self._cohort_relaunches, process_id=p
            )

    def add_worker(self) -> int:
        """Scale up by one worker (elastic scale-out).

        Cohort mode: a live jax.distributed world is fixed-size, so scale-out
        is a deliberate re-formation — the watch loop tears the cohort down
        at the next poll and respawns it one process larger (new coordinator,
        new world version, state restored from the latest checkpoint; global
        batch and LR are invariant — strong scaling). Returns the new target
        size.
        """
        if self._cohort_mode:
            with self._lock:
                target = (self._pending_resize or self._cohort_size) + 1
                self._pending_resize = target
                if self._reform_trace_id is None:
                    self._reform_trace_id = tracing.new_trace_id()
                tid = self._reform_trace_id
                self._announce_locked()
                logger.info("cohort scale-out requested: -> %d processes", target)
            tracing.event(
                "reform.announce", trace_id=tid, pending_size=target,
                direction="up",
            )
            return target
        _reject_plain_training_scale_out(self.cfg)
        with self._lock:
            wid = self._next_worker_id
            self._next_worker_id += 1
            self._procs[wid] = self._spawn(wid)
            return wid

    def remove_worker(self) -> int:
        """Scale down by one process (cohort mode): deliberate re-formation
        at N-1, same mechanics as add_worker."""
        if not self._cohort_mode:
            raise RuntimeError("remove_worker only applies to cohort mode")
        with self._lock:
            target = max(1, (self._pending_resize or self._cohort_size) - 1)
            self._pending_resize = target
            if self._reform_trace_id is None:
                self._reform_trace_id = tracing.new_trace_id()
            tid = self._reform_trace_id
            self._announce_locked()
            logger.info("cohort scale-in requested: -> %d processes", target)
        tracing.event(
            "reform.announce", trace_id=tid, pending_size=target,
            direction="down",
        )
        return target

    def add_reform_observer(self, cb) -> None:
        """cb(seconds, old_size, new_size) after every completed cohort
        re-formation — the autoscaler's cost model feeds its rescale-cost
        EWMA from this. Registration-before-start contract."""
        self._reform_observers.append(cb)

    def _notify_reform(self, seconds: float, old: int, new: int) -> None:
        for cb in self._reform_observers:
            try:
                cb(seconds, old, new)
            except Exception:
                logger.exception("reform observer %r failed (ignored)", cb)

    def evict_worker(self, worker_id: int) -> bool:
        """Policy eviction of a PLAIN worker (master/autoscaler.py; the
        cohort flavor is remove_worker's drain-first resize). Marks the
        slot never-relaunch and DELETED-on-exit — the worker itself
        drains through the heartbeat `evict` bit and exits EX_TEMPFAIL;
        this side only ensures the exit retires the slot instead of
        respawning it, and that a deliberate eviction never reads as a
        failure (all_failed must stay false). No signal is sent here:
        the drain handshake is the servicer's, and killing the process
        would throw away exactly the records the drain retires."""
        with self._lock:
            wp = self._procs.get(worker_id)
            if wp is None or wp.proc.poll() is not None:
                return False
            wp.no_relaunch = True
            wp.evicted = True
            wp.relaunches = self.cfg.relaunch_max + 1
        logger.warning(
            "worker %d marked evicted (policy): drains via the heartbeat "
            "evict bit, exit retires the slot", worker_id,
        )
        return True

    def kill_worker(
        self, worker_id: int, relaunch: bool = True, graceful: bool = False
    ) -> bool:
        """Kill one worker process (also the fault-injection hook).
        graceful=True sends SIGTERM — the k8s-preemption shape: the worker
        drains, checkpoints, and exits EX_TEMPFAIL; False is SIGKILL."""
        with self._lock:
            wp = self._procs.get(worker_id)
            if wp is None or wp.proc.poll() is not None:
                return False
            if not relaunch:
                wp.relaunches = self.cfg.relaunch_max + 1
                wp.no_relaunch = True
            if graceful:
                wp.proc.terminate()
            else:
                wp.proc.kill()
        return True

    # ------------------------------------------------------------------ #

    def _watch_loop(self, poll_s: float = 0.5) -> None:
        """The pod-event watch: detect exits, relaunch or retire."""
        if self._cohort_mode:
            self._watch_cohort_loop(poll_s)
            return
        while not self._stop.is_set():
            with self._lock:
                items = list(self._procs.items())
            for wid, wp in items:
                code = wp.proc.poll()
                if code is None or wp.status in (
                    PodStatus.SUCCEEDED, PodStatus.FAILED, PodStatus.DELETED,
                ):
                    continue
                if code == 0:
                    wp.status = PodStatus.SUCCEEDED
                    logger.info("worker %d exited cleanly", wid)
                    continue
                if self._job_finished_fn():
                    # teardown-phase exits are not failures to recover from
                    wp.status = PodStatus.SUCCEEDED
                    logger.info("worker %d exited (code %s) after job end", wid, code)
                    continue
                if wp.evicted:
                    # policy eviction completing: the worker drained
                    # (records retired under its drain checkpoint) and
                    # exited EX_TEMPFAIL. Retire the slot — DELETED, not
                    # FAILED: a deliberate shrink must never read as "all
                    # workers failed" and abort the job. mark_dead still
                    # runs so any lease the drain could not release
                    # requeues FRONT exactly like a death.
                    wp.status = PodStatus.DELETED
                    if self._membership is not None:
                        self._membership.mark_dead(
                            wid, reason="evicted by autoscale policy")
                    logger.warning(
                        "worker %d eviction complete (exit code %s); slot "
                        "retired", wid, code,
                    )
                    continue
                # failure/preemption path
                if self._membership is not None:
                    self._membership.mark_dead(wid, reason=f"exit code {code}")
                if wp.relaunches < self.cfg.relaunch_max:
                    logger.warning(
                        "worker %d died (code %s); relaunch %d/%d",
                        wid, code, wp.relaunches + 1, self.cfg.relaunch_max,
                    )
                    with self._lock:
                        if self._stop.is_set():
                            # stop() may already have snapshotted _procs for
                            # its kill loop: a relaunch now would leak
                            continue
                        self._procs[wid] = self._spawn(
                            wid, relaunches=wp.relaunches + 1
                        )
                else:
                    wp.status = PodStatus.FAILED
                    logger.error(
                        "worker %d died (code %s); relaunch budget exhausted",
                        wid, code,
                    )
            self._stop.wait(poll_s)

    def _teardown_cohort(self, items, reason: str) -> None:
        """Kill every member and reap; recover the leader's leased tasks via
        membership so the new generation re-leases at the task boundary."""
        if self._membership is not None:
            self._membership.mark_dead(0, reason=reason)
        for _, wp in items:
            if wp.proc.poll() is None:
                wp.proc.kill()
        for _, wp in items:
            try:
                wp.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass

    def _reform_cohort(self, new_size: int, old_size: int, reason: str) -> None:
        """Spawn the next cohort generation, possibly at a different size
        (dynamic world resizing — the rebuild of the reference's Horovod
        re-rendezvous at a new world size, SURVEY §2.1/§3.4). The new world
        restores from the latest checkpoint and keeps the global batch and
        LR unchanged (strong scaling — only per-device slice sizes move)."""
        # monotonic: this delta feeds the reform-duration histogram, and
        # an NTP step through a wall-clock delta would corrupt it (EDL406)
        t0 = time.monotonic()
        # the span wraps the lock (not the reverse) so its exit — a
        # trace.jsonl write — never runs under the control-plane lock
        with tracing.span(
            "reform.spawn", new_size=new_size, old_size=old_size,
        ) as spawn_span:
            with self._lock:
                if self._stop.is_set():
                    # stop() raced us between teardown and re-form: spawning
                    # a fresh generation now would outlive stop()'s kill loop
                    # (it only waits grace_s for the watcher) and leak
                    # workers that run forever — observed as orphan processes
                    # hours after a test's manager.stop()
                    spawn_span.set(outcome="skipped_manager_stopping")
                    logger.info("re-formation skipped: manager stopping")
                    return
                self._world_version += 1
                world_version = self._world_version
                # ENQUEUED inside the lock (disk order = mutation order,
                # like every journaled transition) but awaited OUTSIDE it:
                # in group-commit mode the wait is a bounded window the
                # manager lock must not serialize behind (PR 7 boundary).
                commit = (
                    self._journal.append(
                        "world_version", version=world_version
                    )
                    if self._journal is not None else None
                )
            if commit is not None:
                # ack-after-fsync: the version must be DURABLE before it
                # becomes observable (spawned worker envs, the membership-
                # signal announcement) — a crash here must never let
                # workers see a world version the successor's replay
                # lacks. A failed/poisoned commit raises: the reform
                # aborts un-announced, exactly like a master crash at
                # this instant (the in-memory bump was never observable).
                commit.wait()
            with self._lock:
                if self._stop.is_set():
                    spawn_span.set(outcome="skipped_manager_stopping")
                    logger.info("re-formation skipped: manager stopping")
                    return
                if self._world_version != world_version:
                    # a concurrent reform superseded us while we awaited
                    # durability; its spawn/announce carries the newer
                    # version — ours must not resurrect an older cohort
                    spawn_span.set(outcome="superseded")
                    logger.warning(
                        "re-formation superseded (world v%d -> v%d)",
                        world_version, self._world_version,
                    )
                    return
                self._procs.clear()
                if new_size != old_size:
                    # a deliberate resize opens a fresh in-place relaunch
                    # budget
                    self._cohort_relaunches = 0
                self._spawn_cohort_locked(new_size)
                self.reformation_log.append((t0, old_size, new_size))
                if self._pending_resize is None:
                    # this resize's timeline ends when its world is up; a
                    # QUEUED next resize keeps its own announced trace id
                    self._reform_trace_id = None
                # the resize landed: the announcement now carries the NEW
                # world (pending cleared unless another resize is already
                # queued)
                self._announce_locked()
                _COHORT_SIZE.set(self._cohort_size)
        tracing.set_world_version(world_version)
        _REFORMS.inc(kind="resize" if new_size != old_size else "relaunch")
        reform_s = time.monotonic() - t0
        _REFORM_S.observe(reform_s)
        # feed the autoscaler's cost model (outside the lock; best-effort)
        self._notify_reform(reform_s, old_size, new_size)
        if new_size != old_size:
            logger.warning(
                "cohort RESIZED %d -> %d processes (world v%d): %s",
                old_size, new_size, world_version, reason,
            )
        else:
            logger.warning(
                "cohort relaunched at %d processes (world v%d): %s",
                new_size, world_version, reason,
            )

    def _await_resize_checkpoint(self) -> None:
        """Request a checkpoint (via the wired master hook) and wait for a
        newer one to appear before a deliberate teardown. Best-effort: no
        hook, no checkpoint_dir, or a quiet worker (no new steps) just times
        out and the resize proceeds — same cost as before this existed."""
        if self._checkpoint_request_fn is None or not self.cfg.checkpoint_dir:
            return
        try:
            if self._probe_ckpt_mngr is None:
                # one orbax manager, reused for every resize (each instance
                # holds background threads/handles; per-resize construction
                # would leak them across a long elastic job)
                from elasticdl_tpu.training.checkpoint import CheckpointManager

                self._probe_ckpt_mngr = CheckpointManager(self.cfg.checkpoint_dir)
            mngr = self._probe_ckpt_mngr
            before = mngr.latest_step(refresh=True)
        except Exception:
            logger.exception("resize checkpoint probe failed; skipping quiesce")
            return
        try:
            self._checkpoint_request_fn()
        except Exception:
            logger.exception("resize checkpoint request failed; skipping quiesce")
            return
        deadline = time.time() + self._resize_ckpt_timeout_s
        while time.time() < deadline and not self._stop.is_set():
            if self._job_finished_fn():
                return  # nothing left to protect; caller re-checks job end
            try:
                # refresh: the checkpoint is written by the WORKER processes
                latest = mngr.latest_step(refresh=True)
            except Exception:
                break
            if latest is not None and latest != before:
                logger.info(
                    "pre-resize checkpoint landed at step %s (was %s)",
                    latest, before,
                )
                return
            # local-disk poll by ONE watcher thread, not a fleet retrying a
            # shared service — no herd to jitter: edl-lint: disable=EDL304
            time.sleep(0.2)
        logger.warning(
            "pre-resize checkpoint did not land within %.0fs; resizing anyway",
            self._resize_ckpt_timeout_s,
        )

    def _watch_cohort_loop(self, poll_s: float) -> None:
        """Cohort semantics: the jax.distributed world is all-or-nothing —
        one dead member fails the others, so ANY failure tears the cohort
        down and re-forms it whole (the new world restores from the last
        checkpoint). Three re-formation flavors:

        - in-place relaunch (same size) for transient crashes, up to
          `relaunch_max` generations;
        - budget-free retry for world-formation failures (all failed exits
          are ExitCode.WORLD_FORM_FAILED — coordinator-port races), up to
          `infra_retry_max`;
        - RESIZE: on a member marked no-relaunch (permanently lost host), on
          an exhausted relaunch budget, or on an operator add/remove_worker
          request, the next generation runs at the NEW process count —
          training continues at N-1 instead of stalling, or picks up the new
          capacity at N+1. The job only fails when it cannot even run at
          size 1.

        Policy note (documented limitation): a permanently lost host is only
        KNOWN to be lost through the operator/test API
        (`kill_worker(relaunch=False)` sets no_relaunch). A real lost host is
        indistinguishable from a transient crash, so recovery first burns the
        in-place relaunch budget (each a full world boot, see
        reformation_log / BASELINE.md re-formation latency) before shrinking
        by one. Tune `relaunch_max` down when hosts are more likely to vanish
        than to crash transiently.
        """
        while not self._stop.is_set():
            with self._lock:
                items = list(self._procs.items())
                pending = self._pending_resize
                size_now = self._cohort_size
            codes = {pid: wp.proc.poll() for pid, wp in items}
            failed = [
                pid for pid, c in codes.items() if c is not None and c != 0
            ]
            if not failed:
                with self._lock:
                    # the retried generation has stayed up: the incident is
                    # over, so the next one gets a full budget-free retry
                    # budget (read+reset under the lock — the old unlocked
                    # read raced add/remove_worker; edl-lint EDL101 find)
                    last = (
                        self.reformation_log[-1][0]
                        if self.reformation_log else 0.0
                    )
                    if self._infra_retries and time.time() - last > 60:
                        self._infra_retries = 0
                        logger.info(
                            "world formation recovered; infra retry budget reset"
                        )
            if failed and not self._job_finished_fn():
                members = dict(items)
                lost = [pid for pid in failed if members[pid].no_relaunch]
                infra = all(
                    codes[pid] == ExitCode.WORLD_FORM_FAILED for pid in failed
                )
                # Decide the next generation's size and commit it to
                # _cohort_size under ONE lock hold: a concurrent
                # add/remove_worker landing during the (slow) teardown below
                # then compounds on the new target instead of the stale size.
                with self._lock:
                    size = self._cohort_size
                    if self._pending_resize == pending:
                        self._pending_resize = None
                    if pending is not None and pending != size:
                        target = pending
                        reason = (
                            f"resize requested while member(s) {failed} died"
                        )
                    elif infra and self._infra_retries < self.infra_retry_max:
                        self._infra_retries += 1
                        target = size
                        reason = (
                            f"world-formation failure (infra retry "
                            f"{self._infra_retries}/{self.infra_retry_max}, "
                            f"budget-free)"
                        )
                    elif (
                        not lost
                        and self._cohort_relaunches < self.cfg.relaunch_max
                    ):
                        self._cohort_relaunches += 1
                        target = size
                        reason = (
                            f"transient failure, generation "
                            f"{self._cohort_relaunches}/{self.cfg.relaunch_max}"
                        )
                    else:
                        # Permanently lost member(s) or exhausted budget:
                        # continue at the surviving count instead of failing.
                        # On budget exhaustion shrink by exactly 1 — a single
                        # crash can cascade every member to a nonzero exit
                        # (world collapse), so len(failed) overstates the loss.
                        target = size - (len(lost) if lost else 1)
                        reason = (
                            "lost member(s) " + str(lost or failed)
                            + ("" if lost else " with relaunch budget spent")
                        )
                    if target >= 1:
                        self._cohort_size = target
                    if not infra:
                        # a formed-then-failed world proves the coordinator
                        # path works: fresh infra budget for the next incident
                        self._infra_retries = 0
                with self._lock:
                    if self._reform_trace_id is None:
                        # crash-path reform: no announcement preceded it, so
                        # the timeline starts here
                        self._reform_trace_id = tracing.new_trace_id()
                    reform_tid = self._reform_trace_id
                with tracing.span(
                    "reform", trace_id=reform_tid, reason=reason,
                    old_size=size, new_size=target,
                ):
                    with tracing.span("reform.teardown"):
                        self._teardown_cohort(
                            items, reason=f"cohort member(s) {failed} died"
                        )
                    if target < 1:
                        logger.error(
                            "cohort cannot continue: no survivors to re-form"
                        )
                        for wp in members.values():
                            wp.status = PodStatus.FAILED
                        return
                    self._reform_cohort(target, size, reason)
            elif (
                pending is not None
                and pending != size_now   # snapshot: _cohort_size is locked
                and not self._job_finished_fn()
            ):
                # planned resize of a HEALTHY cohort: quiesce first — ask for
                # a checkpoint and wait for it, so only sub-task progress is
                # redone at the new size (a crash path can't do this; a
                # deliberate one shouldn't skip it)
                with self._lock:
                    reform_tid = (
                        self._reform_trace_id or tracing.new_trace_id()
                    )
                    self._reform_trace_id = reform_tid
                with tracing.span(
                    "reform", trace_id=reform_tid,
                    reason="operator resize request", new_size=pending,
                    old_size=size_now,
                ):
                    with tracing.span("reform.quiesce"):
                        self._await_resize_checkpoint()
                    if self._job_finished_fn():
                        # the job ran out from under the resize: nothing to
                        # do — and this resize's trace id dies with it (a
                        # later reform is a DIFFERENT incident and must
                        # open its own timeline)
                        with self._lock:
                            if self._pending_resize == pending:
                                self._pending_resize = None
                            self._reform_trace_id = None
                            self._announce_locked()
                        continue
                    with self._lock:
                        if self._pending_resize == pending:
                            self._pending_resize = None
                        old = self._cohort_size
                        self._cohort_size = pending
                    with tracing.span("reform.teardown"):
                        self._teardown_cohort(
                            items, reason=f"cohort resize to {pending}"
                        )
                    self._reform_cohort(pending, old, "operator resize request")
            elif all(c is not None for c in codes.values()) and codes:
                with self._lock:
                    for wp in self._procs.values():
                        wp.status = PodStatus.SUCCEEDED
                return
            self._stop.wait(poll_s)

    def request_flight_dump(
        self, worker_id: int, process_index: Optional[int] = None
    ) -> bool:
        """SIGUSR2 a worker's process(es): the flight recorder's explicit
        trigger — the straggler hook's OFFENDER snapshot rides this
        (client/local.py wires it; only the launcher knows pids). Plain
        mode: the proc registered under `worker_id`. Cohort mode: the
        member process at `process_index`, or the whole cohort when None
        (a cohort-level flag with no process attribution). Returns True
        when at least one live process was signalled."""
        with self._lock:
            if self._cohort_mode:
                keys = (
                    [process_index] if process_index is not None
                    else list(self._procs)
                )
            else:
                keys = [worker_id]
            procs = [
                self._procs[k].proc for k in keys if k in self._procs
            ]
        signalled = False
        for proc in procs:
            if proc.poll() is not None:
                continue
            try:
                proc.send_signal(signal.SIGUSR2)
                signalled = True
            except (OSError, ValueError):
                continue
        if signalled:
            logger.info(
                "flight dump requested from worker %d%s (SIGUSR2)",
                worker_id,
                f" process {process_index}" if process_index is not None
                else "",
            )
        return signalled

    # ------------------------------------------------------------------ #

    def stop(self, grace_s: float = 10.0) -> None:
        self._stop.set()
        if self._watcher:
            self._watcher.join(timeout=grace_s)
        if self._probe_ckpt_mngr is not None:
            try:
                self._probe_ckpt_mngr.close()
            except Exception:
                logger.exception("closing resize checkpoint probe failed")
            self._probe_ckpt_mngr = None
        with self._lock:
            procs = list(self._procs.values())
        deadline = time.time() + grace_s
        for wp in procs:
            if wp.proc.poll() is None:
                wp.proc.terminate()
        for wp in procs:
            timeout = max(0.1, deadline - time.time())
            try:
                wp.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                wp.proc.kill()
        # Flush-on-shutdown (closes the PR 7 known boundary): in group-
        # commit mode the newest world_version record may still be riding
        # the committer's bounded window when a clean stop lands — force
        # the open batch to disk NOW so an orderly teardown never loses
        # the version sequence the workers already observed. (The owning
        # Master's close() would drain too, but this manager must not
        # depend on who tears down first.)
        with self._lock:
            journal = self._journal
        if journal is not None:
            try:
                journal.flush()
            except Exception:
                logger.exception("journal flush at manager stop failed")

    def all_exited(self) -> bool:
        with self._lock:
            return all(wp.proc.poll() is not None for wp in self._procs.values())

    def all_failed(self) -> bool:
        """True when every worker that could still make progress is dead
        with its relaunch budget spent — the job cannot continue.
        DELETED (policy-evicted) and SUCCEEDED slots are deliberate
        retirements, not failures: they are EXCLUDED from the scan, or a
        single autoscale eviction would pin this False forever and a
        subsequently all-dead fleet could never abort the launcher's
        wait."""
        with self._lock:
            tracked = [
                wp for wp in self._procs.values()
                if wp.status not in (PodStatus.DELETED, PodStatus.SUCCEEDED)
            ]
            if not tracked:
                return False
            return all(
                wp.status == PodStatus.FAILED and wp.proc.poll() is not None
                for wp in tracked
            )

    def statuses(self) -> Dict[int, str]:
        with self._lock:
            out = {}
            for wid, wp in self._procs.items():
                code = wp.proc.poll()
                out[wid] = (
                    wp.status
                    if code is None
                    else (PodStatus.SUCCEEDED if code == 0 else wp.status)
                )
            return out
