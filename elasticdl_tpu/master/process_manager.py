"""Elastic worker process manager (local-process instance manager).

Reference parity: elasticdl/python/master/k8s_instance_manager.py — create
worker instances, watch their lifecycle, relaunch failures up to
`relaunch_max`, and tell the membership/dispatcher when one dies. This is the
same state machine with subprocesses instead of pods (the k8s flavor renders
pod specs through client/k8s.py); the master's control plane is identical in
both, which is what makes the fault-injection tests honest — they kill real
worker processes, as the reference's integration tests killed real pods.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.net import free_port
from elasticdl_tpu.common.constants import PodStatus, WorkerEnv
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.membership import Membership

logger = default_logger(__name__)


@dataclass
class _WorkerProc:
    worker_id: int
    proc: subprocess.Popen
    relaunches: int = 0
    status: str = PodStatus.RUNNING


class ProcessManager:
    """Spawns and babysits worker subprocesses."""

    def __init__(
        self,
        cfg: JobConfig,
        membership: Optional[Membership] = None,
        extra_env: Optional[Dict[str, str]] = None,
        log_dir: Optional[str] = None,
        job_finished_fn=None,
    ):
        self.cfg = cfg
        self._membership = membership
        self._extra_env = dict(extra_env or {})
        self._log_dir = log_dir
        # when this returns True, worker exits are final — no relaunches
        self._job_finished_fn = job_finished_fn or (lambda: False)
        self._procs: Dict[int, _WorkerProc] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._next_worker_id = 0
        self._cohort_relaunches = 0
        self._cohort_coordinator = ""

    @property
    def _cohort_mode(self) -> bool:
        return self.cfg.num_processes > 1


    # ------------------------------------------------------------------ #

    def _spawn(self, worker_id: int, relaunches: int = 0,
               process_id: int = 0) -> _WorkerProc:
        env = dict(os.environ)
        env.update({str(k): str(v) for k, v in self.cfg.envs.items()})
        env.update(self._extra_env)
        env[WorkerEnv.WORKER_ID] = str(worker_id)
        env[WorkerEnv.MASTER_ADDR] = self.cfg.master_addr
        env[WorkerEnv.NUM_WORKERS] = str(self.cfg.num_workers)
        if self._cohort_mode:
            env["EDL_PROCESS_ID"] = str(process_id)
            env["EDL_COORDINATOR_ADDR"] = self._cohort_coordinator
        argv = self.cfg.to_argv()
        stdout = stderr = None
        if self._log_dir:
            os.makedirs(self._log_dir, exist_ok=True)
            name = (
                f"worker-{worker_id}-p{process_id}.log"
                if self._cohort_mode else f"worker-{worker_id}.log"
            )
            log = open(os.path.join(self._log_dir, name), "ab")
            stdout = stderr = log
        proc = subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.worker.main", *argv],
            env=env,
            stdout=stdout,
            stderr=stderr,
        )
        wp = _WorkerProc(worker_id=worker_id, proc=proc, relaunches=relaunches)
        logger.info("spawned worker %d (pid %d)", worker_id, proc.pid)
        return wp

    def start_workers(self) -> None:
        with self._lock:
            if self._cohort_mode:
                self._spawn_cohort_locked()
            else:
                for _ in range(self.cfg.num_workers):
                    wid = self._next_worker_id
                    self._next_worker_id += 1
                    self._procs[wid] = self._spawn(wid)
        self._watcher = threading.Thread(target=self._watch_loop, daemon=True)
        self._watcher.start()

    def _spawn_cohort_locked(self) -> None:
        """Spawn all cohort members (process id == slot id; the leader,
        process 0, registers with the master as worker 0). A fresh
        coordinator port per generation avoids TIME_WAIT rebind races."""
        self._cohort_coordinator = f"localhost:{free_port()}"
        for p in range(self.cfg.num_processes):
            self._procs[p] = self._spawn(
                0, relaunches=self._cohort_relaunches, process_id=p
            )

    def add_worker(self) -> int:
        """Scale up by one worker (elastic scale-out)."""
        if self._cohort_mode:
            # a live jax.distributed world is fixed-size; scale-out means a
            # new cohort generation with a larger num_processes, not an
            # extra member joining the running coordinator
            raise RuntimeError(
                "add_worker is not supported in cohort mode; change "
                "num_processes and relaunch the cohort instead"
            )
        with self._lock:
            wid = self._next_worker_id
            self._next_worker_id += 1
            self._procs[wid] = self._spawn(wid)
            return wid

    def kill_worker(
        self, worker_id: int, relaunch: bool = True, graceful: bool = False
    ) -> bool:
        """Kill one worker process (also the fault-injection hook).
        graceful=True sends SIGTERM — the k8s-preemption shape: the worker
        drains, checkpoints, and exits EX_TEMPFAIL; False is SIGKILL."""
        with self._lock:
            wp = self._procs.get(worker_id)
            if wp is None or wp.proc.poll() is not None:
                return False
            if not relaunch:
                wp.relaunches = self.cfg.relaunch_max + 1
            if graceful:
                wp.proc.terminate()
            else:
                wp.proc.kill()
        return True

    # ------------------------------------------------------------------ #

    def _watch_loop(self, poll_s: float = 0.5) -> None:
        """The pod-event watch: detect exits, relaunch or retire."""
        if self._cohort_mode:
            self._watch_cohort_loop(poll_s)
            return
        while not self._stop.is_set():
            with self._lock:
                items = list(self._procs.items())
            for wid, wp in items:
                code = wp.proc.poll()
                if code is None or wp.status in (
                    PodStatus.SUCCEEDED, PodStatus.FAILED, PodStatus.DELETED,
                ):
                    continue
                if code == 0:
                    wp.status = PodStatus.SUCCEEDED
                    logger.info("worker %d exited cleanly", wid)
                    continue
                if self._job_finished_fn():
                    # teardown-phase exits are not failures to recover from
                    wp.status = PodStatus.SUCCEEDED
                    logger.info("worker %d exited (code %s) after job end", wid, code)
                    continue
                # failure/preemption path
                if self._membership is not None:
                    self._membership.mark_dead(wid, reason=f"exit code {code}")
                if wp.relaunches < self.cfg.relaunch_max:
                    logger.warning(
                        "worker %d died (code %s); relaunch %d/%d",
                        wid, code, wp.relaunches + 1, self.cfg.relaunch_max,
                    )
                    with self._lock:
                        self._procs[wid] = self._spawn(
                            wid, relaunches=wp.relaunches + 1
                        )
                else:
                    wp.status = PodStatus.FAILED
                    logger.error(
                        "worker %d died (code %s); relaunch budget exhausted",
                        wid, code,
                    )
            self._stop.wait(poll_s)

    def _watch_cohort_loop(self, poll_s: float) -> None:
        """Cohort semantics: the jax.distributed world is all-or-nothing —
        one dead member fails the others, so ANY failure tears the cohort
        down and relaunches it whole (the new world restores from the last
        checkpoint). The relaunch budget counts cohort generations."""
        while not self._stop.is_set():
            with self._lock:
                items = list(self._procs.items())
            codes = {pid: wp.proc.poll() for pid, wp in items}
            failed = [
                pid for pid, c in codes.items() if c is not None and c != 0
            ]
            if failed and not self._job_finished_fn():
                if self._membership is not None:
                    self._membership.mark_dead(
                        0, reason=f"cohort member(s) {failed} died"
                    )
                for pid, wp in items:
                    if wp.proc.poll() is None:
                        wp.proc.kill()
                for pid, wp in items:
                    try:
                        wp.proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pass
                if self._cohort_relaunches < self.cfg.relaunch_max:
                    self._cohort_relaunches += 1
                    logger.warning(
                        "cohort member(s) %s died; relaunching cohort "
                        "(generation %d/%d)",
                        failed, self._cohort_relaunches, self.cfg.relaunch_max,
                    )
                    with self._lock:
                        self._procs.clear()
                        self._spawn_cohort_locked()
                else:
                    logger.error("cohort relaunch budget exhausted")
                    for wp in self._procs.values():
                        wp.status = PodStatus.FAILED
                    return
            elif all(c is not None for c in codes.values()) and codes:
                for wp in self._procs.values():
                    wp.status = PodStatus.SUCCEEDED
                return
            self._stop.wait(poll_s)

    # ------------------------------------------------------------------ #

    def stop(self, grace_s: float = 10.0) -> None:
        self._stop.set()
        if self._watcher:
            self._watcher.join(timeout=grace_s)
        with self._lock:
            procs = list(self._procs.values())
        deadline = time.time() + grace_s
        for wp in procs:
            if wp.proc.poll() is None:
                wp.proc.terminate()
        for wp in procs:
            timeout = max(0.1, deadline - time.time())
            try:
                wp.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                wp.proc.kill()

    def all_exited(self) -> bool:
        with self._lock:
            return all(wp.proc.poll() is not None for wp in self._procs.values())

    def all_failed(self) -> bool:
        """True when every worker is dead with its relaunch budget spent —
        the job cannot make progress anymore."""
        with self._lock:
            if not self._procs:
                return False
            return all(
                wp.status == PodStatus.FAILED and wp.proc.poll() is not None
                for wp in self._procs.values()
            )

    def statuses(self) -> Dict[int, str]:
        with self._lock:
            out = {}
            for wid, wp in self._procs.items():
                code = wp.proc.poll()
                out[wid] = (
                    wp.status
                    if code is None
                    else (PodStatus.SUCCEEDED if code == 0 else wp.status)
                )
            return out
