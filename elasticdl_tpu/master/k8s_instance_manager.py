"""Kubernetes instance manager: master-created worker pods + event watch.

Reference parity: elasticdl/python/master/k8s_instance_manager.py (SURVEY
§2.1) — the master creates worker pods via the k8s API, watches the pod event
stream, relaunches failures up to the budget, and tells membership (and
through it the task dispatcher) when a worker dies. k8s is the failure
detector here, not heartbeats: a FAILED/DELETED event drives task recovery
immediately, while the heartbeat reaper stays as the backstop for pods that
hang without dying.

The process twin is master/process_manager.py — same state machine over
subprocesses; this module is the pod flavor the reference actually shipped.
The k8s API surface is injected (`K8sApi`) so the state machine is unit-
testable against a scripted watch stream (SURVEY §4's in-process-fake
pattern); the shipped implementation, `KubectlApi`, shells to kubectl with
JSON watch-event output — this sandbox has no `kubernetes` Python client, and
kubectl's `--output-watch-events` stream carries the same ADDED/MODIFIED/
DELETED triples the client's watch would.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import yaml

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import PodStatus
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.membership import Membership

logger = default_logger(__name__)


@dataclass
class PodEvent:
    """One pod lifecycle event, normalized from the watch stream."""

    type: str        # ADDED | MODIFIED | DELETED
    name: str        # pod name
    phase: str       # Pending | Running | Succeeded | Failed | Unknown


class K8sApi:
    """The slice of the k8s API the instance manager needs. Injectable so
    tests script the watch; KubectlApi is the production implementation."""

    def create_pod(self, manifest: Dict) -> None:
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def watch_pods(self, label_selector: str, stop: threading.Event
                   ) -> Iterator[PodEvent]:
        raise NotImplementedError


class KubectlApi(K8sApi):
    """kubectl-backed implementation (no `kubernetes` package needed)."""

    def __init__(self, namespace: str = "default"):
        self._ns = namespace
        self._kubectl = shutil.which("kubectl")
        self._watch_procs: List[subprocess.Popen] = []
        if self._kubectl is None:
            raise RuntimeError(
                "kubectl not found on PATH; the k8s instance manager needs "
                "it (or inject a K8sApi)"
            )

    def create_pod(self, manifest: Dict) -> None:
        proc = subprocess.run(
            [self._kubectl, "-n", self._ns, "apply", "-f", "-"],
            input=yaml.safe_dump(manifest).encode(),
            capture_output=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(f"pod create failed: {proc.stderr.decode()}")

    def delete_pod(self, name: str) -> None:
        subprocess.run(
            [self._kubectl, "-n", self._ns, "delete", "pod", name,
             "--ignore-not-found", "--wait=false"],
            capture_output=True,
        )

    def watch_pods(self, label_selector: str, stop: threading.Event
                   ) -> Iterator[PodEvent]:
        """`kubectl get pods --watch --output-watch-events -o json` emits one
        JSON document per event: {"type": ..., "object": <Pod>}. The read
        loop selects with a short timeout so `stop` is observed within
        ~0.5 s even when no events arrive (a blocking read1 would pin the
        watcher thread until the next pod event); close() kills any
        outstanding kubectl child."""
        import codecs
        import select

        proc = subprocess.Popen(
            [
                self._kubectl, "-n", self._ns, "get", "pods",
                "-l", label_selector, "--watch", "--output-watch-events",
                "-o", "json",
            ],
            stdout=subprocess.PIPE,
        )
        self._watch_procs.append(proc)
        decoder = json.JSONDecoder()
        # incremental decode: a multi-byte UTF-8 sequence (pod annotations,
        # event messages) split across a read boundary must not raise and
        # tear the watch stream down
        utf8 = codecs.getincrementaldecoder("utf-8")(errors="replace")
        buf = ""
        try:
            while not stop.is_set():
                ready, _, _ = select.select([proc.stdout], [], [], 0.5)
                if not ready:
                    if proc.poll() is not None:
                        break  # kubectl exited with nothing buffered
                    continue
                raw = proc.stdout.read1(65536)
                if not raw:
                    break
                buf += utf8.decode(raw)
                while True:
                    buf = buf.lstrip()
                    if not buf:
                        break
                    try:
                        obj, idx = decoder.raw_decode(buf)
                    except json.JSONDecodeError:
                        break  # partial document; read more
                    buf = buf[idx:]
                    pod = obj.get("object", {})
                    yield PodEvent(
                        type=obj.get("type", ""),
                        name=pod.get("metadata", {}).get("name", ""),
                        phase=pod.get("status", {}).get("phase", "Unknown"),
                    )
        finally:
            proc.kill()
            if proc in self._watch_procs:
                self._watch_procs.remove(proc)

    def close(self) -> None:
        """Kill outstanding kubectl --watch children (the generator's
        finally may never run if its thread is parked on a dead stream)."""
        for proc in list(self._watch_procs):
            try:
                proc.kill()
            except OSError:
                pass


class K8sInstanceManager:
    """Create/watch/relaunch worker pods; drive task recovery on pod death.

    Same interface shape as ProcessManager (start_workers/add_worker/stop/
    statuses/all_exited/all_failed) so master wiring and tests treat the two
    flavors interchangeably.
    """

    def __init__(
        self,
        cfg: JobConfig,
        membership: Optional[Membership] = None,
        api: Optional[K8sApi] = None,
        job_finished_fn=None,
    ):
        from elasticdl_tpu.client.k8s import JOB_LABEL

        self.cfg = cfg
        self._membership = membership
        self._api = api if api is not None else KubectlApi(cfg.namespace)
        self._job_finished_fn = job_finished_fn or (lambda: False)
        self._label = f"{JOB_LABEL}={cfg.job_name}"
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._status: Dict[int, str] = {}            # guarded_by: _lock
        self._relaunches: Dict[int, int] = {}        # guarded_by: _lock
        # Pod names carry a per-worker GENERATION suffix (worker-<id>-g<N>):
        # a relaunch under the SAME name would `kubectl apply` onto the dead
        # Failed pod object and no-op (no new container), and late DELETED
        # events for old pods would be misattributed to the healthy
        # replacement. Fresh names make relaunches real and stale events
        # distinguishable.
        self._gen: Dict[int, int] = {}               # guarded_by: _lock
        # deliberately removed workers terminate as DELETED, not FAILED
        self._removed: set = set()                   # guarded_by: _lock
        self._next_worker_id = 0                     # guarded_by: _lock

    # ------------------------------------------------------------------ #

    def _pod_name(self, worker_id: int, gen: Optional[int] = None) -> str:  # holds: _lock
        g = self._gen.get(worker_id, 0) if gen is None else gen
        return f"{self.cfg.job_name}-worker-{worker_id}-g{g}"

    def _parse_pod(self, pod_name: str) -> Optional[Tuple[int, int]]:
        """pod name -> (worker_id, generation), or None for foreign pods."""
        prefix = f"{self.cfg.job_name}-worker-"
        if not pod_name.startswith(prefix):
            return None
        rest = pod_name[len(prefix):]
        wid_s, sep, gen_s = rest.rpartition("-g")
        if not sep:
            return None
        try:
            return int(wid_s), int(gen_s)
        except ValueError:
            return None

    def _create(self, worker_id: int, name: str) -> None:
        """API call only — callers reserve status/name under the lock first;
        kubectl I/O (up to its ~30 s request timeout) must never run under
        self._lock or it freezes status polls and event handling."""
        from elasticdl_tpu.client.k8s import render_worker_pod

        self._api.create_pod(render_worker_pod(self.cfg, worker_id, pod_name=name))
        logger.info("created worker pod %s", name)

    def start_workers(self) -> None:
        with self._lock:
            names = []
            for _ in range(self.cfg.num_workers):
                wid = self._next_worker_id
                self._next_worker_id += 1
                self._status[wid] = PodStatus.PENDING
                names.append((wid, self._pod_name(wid)))
        for wid, name in names:
            try:
                self._create(wid, name)
            except Exception:
                logger.exception("initial create of worker %d failed", wid)
                with self._lock:
                    self._status[wid] = PodStatus.FAILED
        self._watcher = threading.Thread(target=self._watch_loop, daemon=True)
        self._watcher.start()

    def add_worker(self) -> int:
        """Elastic scale-out: one more worker pod (reference parity: the pod
        manager growing the worker set; membership version bumps when the new
        pod registers). Training jobs are rejected — plain pods have no
        gradient exchange (see process_manager's runtime guard)."""
        from elasticdl_tpu.master.process_manager import (
            _reject_plain_training_scale_out,
        )

        _reject_plain_training_scale_out(self.cfg)
        with self._lock:
            wid = self._next_worker_id
            self._next_worker_id += 1
            self._status[wid] = PodStatus.PENDING
            name = self._pod_name(wid)
        self._create(wid, name)
        return wid

    def remove_worker(self, worker_id: int) -> None:
        """Deliberate scale-in: delete the pod; the DELETED event (not this
        call) drives lease recovery so the path is identical to eviction —
        but the worker terminates as DELETED, not FAILED (a scale-in is not
        a failure and must not trip all_failed())."""
        with self._lock:
            self._removed.add(worker_id)
            name = self._pod_name(worker_id)
        self._api.delete_pod(name)

    # ------------------------------------------------------------------ #

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                for event in self._api.watch_pods(self._label, self._stop):
                    if self._stop.is_set():
                        break
                    self._handle_event(event)
            except Exception:
                if self._stop.is_set():
                    break
                logger.exception("pod watch stream failed; reconnecting")
            # the watch stream ended (kubectl restart, apiserver hiccup):
            # reconnect unless stopping
            self._stop.wait(1.0)

    def _handle_event(self, event: PodEvent) -> None:
        parsed = self._parse_pod(event.name)
        if parsed is None:
            return
        wid, gen = parsed
        with self._lock:
            if gen != self._gen.get(wid, 0):
                # stale event from a previous generation's pod (e.g. the GC
                # deleting a Failed pod we already replaced): ignore — acting
                # on it would kill the healthy replacement's leases
                return
        if event.type in ("ADDED", "MODIFIED"):
            if event.phase == "Running":
                with self._lock:
                    self._status[wid] = PodStatus.RUNNING
            elif event.phase == "Succeeded":
                with self._lock:
                    self._status[wid] = PodStatus.SUCCEEDED
            elif event.phase == "Failed":
                with self._lock:
                    # same terminal guard as DELETED: a budget-exhausted
                    # worker's Failed pod lingers in the cluster (no relaunch
                    # deletes it), and every watch reconnect re-lists it as
                    # ADDED/Failed for the same generation — without this,
                    # each reconnect re-fires _on_pod_death (repeat
                    # mark_dead; status corruption once the job finishes)
                    terminal = self._status.get(wid) in (
                        PodStatus.SUCCEEDED, PodStatus.FAILED,
                        PodStatus.DELETED,
                    )
                if not terminal:
                    self._on_pod_death(wid, f"pod {event.name} Failed")
        elif event.type == "DELETED":
            with self._lock:
                terminal = self._status.get(wid) in (
                    PodStatus.SUCCEEDED, PodStatus.FAILED, PodStatus.DELETED,
                )
            if not terminal:
                self._on_pod_death(wid, f"pod {event.name} deleted")

    def _on_pod_death(self, wid: int, reason: str) -> None:
        """Pod death IS the failure signal (no heartbeat lapse needed):
        recover the worker's leased tasks now, then relaunch within budget."""
        if self._job_finished_fn():
            with self._lock:
                self._status[wid] = PodStatus.SUCCEEDED
            return
        if self._membership is not None:
            # mark_dead fires the dispatcher's recover_tasks callback —
            # this is what makes recovery watch-driven, not timeout-driven
            self._membership.mark_dead(wid, reason=reason)
        # decide under the lock, perform kubectl I/O outside it
        with self._lock:
            if wid in self._removed:
                # deliberate scale-in completing: terminal, not a failure
                self._status[wid] = PodStatus.DELETED
                logger.info("%s; worker %d removed (scale-in)", reason, wid)
                return
            relaunches = self._relaunches.get(wid, 0)
            if relaunches >= self.cfg.relaunch_max:
                self._status[wid] = PodStatus.FAILED
                logger.error("%s; relaunch budget exhausted", reason)
                return
            self._relaunches[wid] = relaunches + 1
            old_name = self._pod_name(wid)
            self._gen[wid] = self._gen.get(wid, 0) + 1
            new_name = self._pod_name(wid)
            self._status[wid] = PodStatus.PENDING
        logger.warning(
            "%s; relaunch %d/%d as %s", reason,
            relaunches + 1, self.cfg.relaunch_max, new_name,
        )
        try:
            # clean up the dead object (ignore-not-found), then create the
            # next generation under its fresh name
            self._api.delete_pod(old_name)
            self._create(wid, new_name)
        except Exception:
            logger.exception("relaunch of worker %d failed", wid)
            with self._lock:
                self._status[wid] = PodStatus.FAILED

    # ------------------------------------------------------------------ #

    def stop(self, grace_s: float = 10.0) -> None:
        self._stop.set()
        close = getattr(self._api, "close", None)
        if close is not None:
            close()  # unblocks a watcher parked on the kubectl stream
        if self._watcher is not None:
            self._watcher.join(timeout=grace_s)
        with self._lock:
            names = [self._pod_name(wid) for wid in self._status]
        for name in names:
            try:
                self._api.delete_pod(name)
            except Exception:
                logger.exception("delete of %s failed", name)

    def statuses(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._status)

    def all_exited(self) -> bool:
        with self._lock:
            return bool(self._status) and all(
                s in (PodStatus.SUCCEEDED, PodStatus.FAILED, PodStatus.DELETED)
                for s in self._status.values()
            )

    def all_failed(self) -> bool:
        # DELETED (deliberately removed/evicted — K8sInstanceTarget's
        # eviction path lands here) and SUCCEEDED pods are retirements,
        # not failures: excluded, or one eviction pins this False while
        # the rest of the fleet dies (process_manager.all_failed's twin)
        with self._lock:
            tracked = [
                s for s in self._status.values()
                if s not in (PodStatus.DELETED, PodStatus.SUCCEEDED)
            ]
            return bool(tracked) and all(
                s == PodStatus.FAILED for s in tracked
            )
