"""Evaluation scheduling and cross-worker metric aggregation.

Reference parity: elasticdl/python/master/evaluation_service.py — the master
triggers an evaluation job every `evaluation_steps` completed training tasks
(or at epoch end), workers run the eval tasks, and the master aggregates
their reports into job metrics. The reference shipped raw model outputs +
labels to the master; here workers send fixed-size *additive metric states*
(see training/metrics.py) so aggregation is a vector sum and the wire cost is
O(metrics), not O(dataset).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

logger = default_logger(__name__)


class _EvalJob:
    def __init__(self, job_id: int, num_tasks: int, model_version: int):
        self.job_id = job_id
        self.num_tasks = num_tasks
        self.reported_task_ids: set = set()
        self.model_version = model_version
        self.states: Dict[str, np.ndarray] = {}

    @property
    def complete(self) -> bool:
        return len(self.reported_task_ids) >= self.num_tasks


class EvaluationService:
    def __init__(
        self,
        dispatcher: TaskDispatcher,
        metrics: Optional[Dict[str, object]] = None,  # name -> Metric
        evaluation_steps: int = 0,
        start_delay_steps: int = 0,
    ):
        self._lock = threading.Lock()
        self._dispatcher = dispatcher
        self._metrics = metrics or {}
        self._evaluation_steps = evaluation_steps
        self._start_delay = start_delay_steps
        self._next_job_id = 0                        # guarded_by: _lock
        self._jobs: Dict[int, _EvalJob] = {}         # guarded_by: _lock
        self._last_trigger_version = 0               # guarded_by: _lock
        self._latest_model_version = 0               # guarded_by: _lock
        self._latest_results: Dict[str, float] = {}  # guarded_by: _lock
        # registration-before-start contract; fired outside the lock
        self._result_callbacks: List[Callable[[int, Dict[str, float]], None]] = []
        dispatcher.add_epoch_end_callback(self._on_epoch_end)
        dispatcher.add_task_failed_callback(self._on_task_failed)

    def add_result_callback(
        self, cb: Callable[[int, Dict[str, float]], None]
    ) -> None:
        """cb(model_version, results) on each completed eval job — the hook
        early-stopping / best-checkpoint callbacks attach to."""
        self._result_callbacks.append(cb)

    # ------------------------------------------------------------------ #

    def maybe_trigger(self, model_version: Optional[int] = None) -> Optional[int]:
        """Called after each finished training task; starts an eval job every
        `evaluation_steps` MODEL-VERSION steps (minibatches — the reference's
        unit for --evaluation_steps; workers report their model_version with
        each task result, so the servicer passes it here). Falls back to the
        completed-task counter when no version is supplied (tests, legacy
        callers). The threshold check claims `_last_trigger_version` under
        the lock so concurrent report handlers can't double-trigger."""
        version = (
            model_version
            if model_version is not None
            else self._dispatcher.completed_versions
        )
        with self._lock:
            # tracked even when interval evals are off: epoch-end evals use
            # it so their scalars land on the same model_version axis as the
            # train-loss stream
            self._latest_model_version = max(self._latest_model_version, version)
        if not self._evaluation_steps:
            return None
        with self._lock:
            if version < self._start_delay:
                return None
            if version < self._last_trigger_version:
                # the step counter went BACKWARDS: a worker relaunched
                # without a checkpoint to restore (fresh model_version).
                # Re-base the threshold or evals would silently stop for
                # last_trigger_version - version further steps.
                logger.warning(
                    "model_version regressed %d -> %d (worker relaunch "
                    "without restore); re-basing eval trigger",
                    self._last_trigger_version, version,
                )
                self._last_trigger_version = version
                return None
            if version - self._last_trigger_version < self._evaluation_steps:
                return None
            self._last_trigger_version = version
        return self.trigger(version)

    def _on_epoch_end(self, epoch: int) -> None:
        with self._lock:
            version = max(
                self._latest_model_version, self._dispatcher.completed_versions
            )
        self.trigger(version)

    def trigger(self, model_version: int) -> Optional[int]:
        # register the job BEFORE its tasks hit the queue — a fast worker can
        # lease + report one before create_evaluation_tasks returns
        n = self._dispatcher.num_evaluation_tasks()
        if n == 0:
            return None
        with self._lock:
            job_id = self._next_job_id
            self._next_job_id += 1
            self._last_trigger_version = model_version
            self._jobs[job_id] = _EvalJob(job_id, n, model_version)
        self._dispatcher.create_evaluation_tasks(job_id)
        logger.info("triggered eval job %d at version %d", job_id, model_version)
        return job_id

    def report_metrics(
        self, eval_job_id: int, task_id: int, states: Dict[str, np.ndarray]
    ) -> None:
        """Merge one eval *task*'s metric states (additive). Duplicate
        reports of a task (lease expiry + re-execution) are dropped."""
        done: Optional[_EvalJob] = None
        with self._lock:
            job = self._jobs.get(eval_job_id)
            if job is None:
                logger.warning("metrics for unknown eval job %d", eval_job_id)
                return
            if task_id in job.reported_task_ids:
                logger.info(
                    "duplicate metrics for eval job %d task %d ignored",
                    eval_job_id, task_id,
                )
                return
            job.reported_task_ids.add(task_id)
            for name, state in states.items():
                if name in job.states:
                    job.states[name] = job.states[name] + np.asarray(state)
                else:
                    job.states[name] = np.asarray(state).copy()
            if job.complete:
                done = self._jobs.pop(eval_job_id)
        if done is not None:
            self._finalize(done)

    def _on_task_failed(self, task) -> None:
        """A permanently failed eval task can never report — shrink the
        job's expectation so it still finalizes."""
        from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

        if task.type != pb.EVALUATION:
            return
        done: Optional[_EvalJob] = None
        with self._lock:
            job = self._jobs.get(task.eval_job_id)
            if job is None:
                return
            job.num_tasks -= 1
            logger.warning(
                "eval job %d lost task %d permanently; expecting %d tasks",
                job.job_id, task.task_id, job.num_tasks,
            )
            if job.complete:
                done = self._jobs.pop(job.job_id)
        if done is not None:
            self._finalize(done)

    def _finalize(self, job: _EvalJob) -> None:
        results: Dict[str, float] = {}
        for name, metric in self._metrics.items():
            if name in job.states:
                results[name] = float(metric.result(job.states[name]))
        loss_state = job.states.get("_loss")
        if loss_state is not None and loss_state[1] > 0:
            results["loss"] = float(loss_state[0] / loss_state[1])
        with self._lock:
            self._latest_results = results
        logger.info(
            "eval job %d done (model v%d): %s", job.job_id, job.model_version, results
        )
        for cb in self._result_callbacks:
            cb(job.model_version, results)

    def latest_results(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._latest_results)
