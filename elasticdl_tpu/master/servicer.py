"""Master gRPC servicer: the single control-plane endpoint workers talk to.

Reference parity: elasticdl/python/master/servicer.py (MasterServicer —
get_task / report_task_result / report_evaluation_metrics / report_version).
Membership RPCs replace what the reference delegated to k8s pod events plus
the Horovod rendezvous: RegisterWorker + Heartbeat carry the
membership_version that drives elastic mesh re-formation.
"""

from __future__ import annotations

import threading
from typing import Optional

import grpc
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.membership import Membership
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.observability import health as health_lib
from elasticdl_tpu.observability.registry import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.proto.service import GENERATION_KEY, REREGISTER_KEY

logger = default_logger(__name__)

_reg = default_registry()
_STALE_GEN_REJECTS = _reg.counter(
    "edl_master_stale_generation_rejects_total",
    "RPCs fenced for claiming a pre-restart master generation",
    labels=("method",))
_REREGISTERS = _reg.counter(
    "edl_master_reregistrations_total",
    "idempotent worker re-registrations (reconnect handshakes)")


class MasterServicer:
    def __init__(
        self,
        dispatcher: TaskDispatcher,
        membership: Membership,
        evaluation_service: Optional[EvaluationService] = None,
        wait_backoff_s: float = 2.0,
        summary_service=None,
        generation: int = 0,
        embedding=None,
    ):
        self._dispatcher = dispatcher
        self._membership = membership
        self._evaluation = evaluation_service
        self._summary = summary_service
        self._wait_backoff_s = wait_backoff_s
        # embedding tier shard-map owner (embedding/sharding.ShardMapOwner;
        # None = tier off — the RPCs answer empty)
        self._embedding = embedding
        # Master generation (master/journal.py header; 0 = fencing off).
        # Workers claim the generation they registered under on every call;
        # a claim from before the last master restart is fenced below so a
        # pre-crash task report can never double-count against the replayed
        # queue state. Stamped onto trailing metadata by proto/service.py.
        self.generation = generation
        self._loss_lock = threading.Lock()
        self._loss_sum = 0.0                # guarded_by: _loss_lock
        self._loss_count = 0                # guarded_by: _loss_lock
        # control-plane flags: mutated by gRPC handler threads (Heartbeat)
        # AND master-side callers (request_checkpoint from the resize
        # quiesce) — the old lock-free set.add/discard raced (edl-lint
        # EDL101 find); worker ids that should checkpoint
        self._ctrl_lock = threading.Lock()
        self._checkpoint_requested = set()  # guarded_by: _ctrl_lock
        # worker ids evicted by the closed-loop autoscaler: STICKY (not
        # one-shot like the checkpoint bit) — a heartbeat response can be
        # dropped on the wire, and a lost one-shot eviction would leave
        # the straggler degrading the fleet forever. The worker's drain
        # is idempotent, so repeats are free; the set is pruned when the
        # worker leaves the membership.
        self._evict_requested = set()       # guarded_by: _ctrl_lock
        self._lr_override = 0.0             # 0 = no master-pushed LR
        self._shutdown = False

    # ------------------------------------------------------------------ #
    # generation fencing (the server half of the handshake)

    @staticmethod
    def _request_metadata(context) -> dict:
        """Invocation metadata as a dict; {} for contexts without it
        (direct in-process servicer calls in tests pass context=None)."""
        if context is None:
            return {}
        try:
            return {k: v for k, v in (context.invocation_metadata() or ())}
        except Exception:
            # metadata is the handshake channel, not the RPC payload; a
            # context that can't supply it is an unfenced caller:
            # edl-lint: disable=EDL303
            return {}

    def _fence_generation(self, method: str, context,
                          on_fence=None) -> None:
        """Abort with a retriable FAILED_PRECONDITION when the caller
        claims a master generation other than this master's. The claim is
        optional (no claim = unfenced legacy caller); the mismatch aborts
        BEFORE any state mutation, so nothing leased or reported under the
        dead master's generation ever reaches the replayed queues. Workers
        react by re-registering (a generation-free RegisterWorker with
        REREGISTER_KEY), not by dying — see proto/service.py
        is_stale_generation.

        `on_fence` runs just before the abort — the wasted-work ledger's
        hook (a fenced ReportTaskResult is finished work being
        discarded). Best-effort: a failing hook never unfences the
        call."""
        if not self.generation or context is None:
            return
        claimed = self._request_metadata(context).get(GENERATION_KEY)
        if claimed is None:
            return
        try:
            claimed = int(claimed)
        except (TypeError, ValueError):
            return
        if claimed != self.generation:
            _STALE_GEN_REJECTS.inc(method=method)
            logger.warning(
                "%s fenced: stale master generation %d (current %d)",
                method, claimed, self.generation,
            )
            if on_fence is not None:
                try:
                    on_fence()
                except Exception:
                    # accounting is advisory; the fence must still land:
                    # edl-lint: disable=EDL303
                    logger.exception("fence accounting hook failed")
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"stale master generation {claimed} (current "
                f"{self.generation}); re-register to continue",
            )

    # ------------------------------------------------------------------ #
    # rpc handlers (name-matched by proto/service.py)

    def RegisterWorker(self, request, context):
        # a register CLAIMING a stale generation is fenced like any other
        # call — the reconnect handshake clears the claim first
        self._fence_generation("RegisterWorker", context)
        preferred = request.preferred_id_plus_one - 1
        data_addr = str(getattr(request, "data_plane_addr", "") or "")
        if (
            self._request_metadata(context).get(REREGISTER_KEY) == "1"
            and preferred >= 0
        ):
            # reconnect of an existing member (e.g. after a master
            # restart): idempotent — a live worker keeps its id and bumps
            # nothing, a reaped one is revived; never a duplicate join
            info = self._membership.reregister(
                preferred, request.worker_name, data_addr=data_addr)
            _REREGISTERS.inc()
        else:
            info = self._membership.register(
                request.worker_name, preferred, data_addr=data_addr)
        member_ids = []
        if request.member_names:
            # cohort-aggregated membership: the leader's member processes
            # join in the SAME round-trip (one lock pass, one journal
            # commit, no version bumps) — idempotent across re-registers
            members = self._membership.register_members(
                info.worker_id, list(request.member_names)
            )
            member_ids = [m.worker_id for m in members]
        return pb.RegisterWorkerResponse(
            worker_id=info.worker_id,
            membership_version=self._membership.version,
            num_workers=self._membership.alive_count(),
            member_ids=member_ids,
        )

    #: server-side ceiling on max_tasks: a misconfigured (or hostile)
    #: worker must not drain the whole queue into one lease batch — every
    #: leased task's timeout clock starts NOW, and a huge batch would
    #: expire its own tail
    MAX_LEASE_BATCH = 256

    def GetTask(self, request, context):
        self._fence_generation("GetTask", context)
        if self._dispatcher.finished():
            return pb.GetTaskResponse(job_done=True)
        # max_tasks == 0 is an old worker (proto3 default): classic
        # one-lease protocol. The response is released only after the
        # lease batch's journal commit fsyncs (ack-after-fsync inside
        # get_many) — nothing a worker ever runs can be lost by a crash.
        n = min(max(1, request.max_tasks), self.MAX_LEASE_BATCH)
        tasks = self._dispatcher.get_many(request.worker_id, n)
        if not tasks:
            return pb.GetTaskResponse(
                task=pb.Task(type=pb.WAIT),
                backoff_seconds=self._wait_backoff_s,
                job_done=self._dispatcher.finished(),
            )
        protos = [t.to_proto() for t in tasks]
        # `task` mirrors the first lease for old workers (which never set
        # max_tasks and never read `tasks`)
        return pb.GetTaskResponse(task=protos[0], tasks=protos)

    def ReportTaskResult(self, request, context):
        # a fenced report is COMPLETED work the fence discards (the
        # replayed lease re-runs it whole): bill the wasted-work ledger
        # before aborting — docs/observability.md "Goodput ledger"
        self._fence_generation(
            "ReportTaskResult", context,
            on_fence=lambda: self._dispatcher.note_fenced_report(
                request.task_id, request.records_processed,
            ),
        )
        accepted = self._dispatcher.report(
            request.task_id,
            request.worker_id,
            request.success,
            request.err_message,
            preempted=request.preempted,
            records_processed=request.records_processed,
        )
        if accepted and request.loss_count:
            # stale/duplicate reports must not skew the job's mean loss
            with self._loss_lock:
                self._loss_sum += request.loss_sum
                self._loss_count += request.loss_count
            if self._summary is not None:
                self._summary.on_task_report(
                    request.model_version, request.loss_sum, request.loss_count,
                    step_time_sum=request.step_time_sum,
                    step_count=request.step_count,
                )
        if accepted and request.success and self._evaluation is not None:
            # model_version is the worker's minibatch-step counter — the
            # reference's evaluation_steps unit (round-3 fix: this used to
            # count completed *tasks*, ~64x coarser at default task sizes)
            self._evaluation.maybe_trigger(request.model_version)
        return pb.ReportTaskResultResponse(accepted=accepted)

    def ReportEvaluationMetrics(self, request, context):
        self._fence_generation("ReportEvaluationMetrics", context)
        if self._evaluation is not None:
            states = {
                s.name: np.frombuffer(s.data, np.float32) for s in request.states
            }
            self._evaluation.report_metrics(
                request.eval_job_id, request.task_id, states
            )
        return pb.ReportEvaluationMetricsResponse()

    def Heartbeat(self, request, context):
        self._fence_generation("Heartbeat", context)
        # optional piggybacked worker telemetry (observability/health.py):
        # decode_stats never raises — an old worker (no payload), a newer
        # one (unknown schema), or garbage all degrade to liveness-only
        stats = health_lib.decode_stats(
            self._request_metadata(context).get(health_lib.STATS_METADATA_KEY)
        )
        # coalesced member beats (cohort leaders): decode_stats bounds
        # each payload the same way it bounds the metadata flavor — a
        # garbage member payload degrades THAT member to liveness-only
        members = [
            (m.worker_id, m.model_version,
             health_lib.decode_stats(m.stats_json))
            for m in request.members
        ]
        known = self._membership.heartbeat(
            request.worker_id, request.model_version, stats=stats,
            members=members or None,
        )
        with self._ctrl_lock:
            # one atomic test-and-clear: the flag is one-shot, and two
            # concurrent heartbeats from a relaunching worker must not both
            # consume (or both miss) the same request
            should_ckpt = request.worker_id in self._checkpoint_requested
            self._checkpoint_requested.discard(request.worker_id)
            evict = request.worker_id in self._evict_requested
        return pb.HeartbeatResponse(
            membership_version=self._membership.version,
            num_workers=self._membership.alive_count(),
            should_checkpoint=should_ckpt,
            shutdown=self._shutdown or not known,
            job_done=self._dispatcher.finished(),
            learning_rate=self._lr_override,
            evict=evict,
        )

    def set_learning_rate(self, lr: float) -> None:
        """Master-side LR override, delivered to every worker on its next
        heartbeat (job callbacks — ReduceLROnPlateau — call this)."""
        self._lr_override = float(lr)

    def GetEmbeddingShardMap(self, request, context):
        """The tier's control-plane read: the current (journal-durable)
        shard map. Bootstraps lazily on the first fetch once workers are
        alive — the map's owner set is the live logical-worker set."""
        self._fence_generation("GetEmbeddingShardMap", context)
        if self._embedding is None:
            return pb.GetEmbeddingShardMapResponse()
        view = self._embedding.view()
        if not view.owners:
            alive = [
                w.worker_id for w in self._membership.alive_workers()
                if w.led_by is None
            ]
            if not alive:
                # nobody to own shards yet: the caller backs off and
                # re-fetches (version 0 = no map)
                return pb.GetEmbeddingShardMapResponse()
            view = self._embedding.bootstrap(alive)
        resp = pb.GetEmbeddingShardMapResponse(
            version=view.version,
            num_shards=view.num_shards,
            shard_owners=list(view.owners),
            resharding=view.resharding,
        )
        # read replicas ride the same response as a flat -1-padded
        # stride of replica_count per shard (see the .proto note)
        rc = max((len(view.replicas_of(s))
                  for s in range(view.num_shards)), default=0)
        if rc:
            resp.replica_count = rc
            flat = []
            for s in range(view.num_shards):
                r = list(view.replicas_of(s))
                flat.extend(r + [-1] * (rc - len(r)))
            resp.shard_replicas.extend(flat)
        for t in view.tables:
            resp.tables.add(
                name=t.name, vocab=t.vocab, dim=t.dim, seed=t.seed,
                init_scale=t.init_scale,
            )
        # the layout controller's ultra-hot set (ISSUE 20) rides the
        # same response; workers pin these rows and keep them fresh
        # through the delta-sync lane
        if view.hot_ids:
            resp.hot_ids.extend(view.hot_ids)
        # owner address book (ISSUE 15): every alive worker's embedding
        # data-plane endpoint rides the map response — GrpcTransport
        # clients adopt it on every refresh, so a relaunched owner's new
        # address propagates on the same cadence as ownership itself
        for wid, addr in self._membership.data_addresses():
            resp.addr_worker_ids.append(wid)
            resp.addrs.append(addr)
        return resp

    def ReportEmbeddingReshard(self, request, context):
        """A recipient confirms installed shard migrations; the plan
        commits (one journal record, acked after fsync inside
        confirm_moves) when every planned move is confirmed."""
        self._fence_generation("ReportEmbeddingReshard", context)
        if self._embedding is None:
            return pb.ReportEmbeddingReshardResponse(accepted=False)
        accepted = self._embedding.confirm_moves(
            request.version, list(request.shard_ids)
        )
        return pb.ReportEmbeddingReshardResponse(accepted=accepted)

    def GetJobStatus(self, request, context):
        counts = self._dispatcher.counts()
        resp = pb.JobStatusResponse(
            job_done=self._dispatcher.finished(),
            finished_training_tasks=counts["finished_training"],
            pending_tasks=counts["todo"],
            doing_tasks=counts["doing"],
            epoch=counts["epoch"],
            membership_version=self._membership.version,
        )
        if self._evaluation is not None:
            for k, v in self._evaluation.latest_results().items():
                resp.eval_metrics[k] = v
        return resp

    # ------------------------------------------------------------------ #

    def request_checkpoint(self, worker_id: int) -> None:
        with self._ctrl_lock:
            self._checkpoint_requested.add(worker_id)

    def request_evict(self, worker_id: int) -> None:
        """The wire half of the graceful-eviction drain handshake
        (master/autoscaler.py): the worker's next heartbeat response
        carries evict=True and it drains through its preempt path —
        checkpoint + preempted report, so in-flight records retire
        instead of re-training — then exits EX_TEMPFAIL."""
        with self._ctrl_lock:
            self._evict_requested.add(worker_id)
        logger.warning(
            "eviction requested for worker %d (drain handshake armed)",
            worker_id,
        )

    def evict_pending(self, worker_id: int) -> bool:
        with self._ctrl_lock:
            return worker_id in self._evict_requested

    def clear_evict(self, worker_id: int) -> None:
        """Prune a completed eviction (the worker left the membership)."""
        with self._ctrl_lock:
            self._evict_requested.discard(worker_id)

    def request_shutdown(self) -> None:
        self._shutdown = True

    def mean_training_loss(self) -> Optional[float]:
        with self._loss_lock:
            if not self._loss_count:
                return None
            return self._loss_sum / self._loss_count
