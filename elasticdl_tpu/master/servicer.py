"""Master gRPC servicer: the single control-plane endpoint workers talk to.

Reference parity: elasticdl/python/master/servicer.py (MasterServicer —
get_task / report_task_result / report_evaluation_metrics / report_version).
Membership RPCs replace what the reference delegated to k8s pod events plus
the Horovod rendezvous: RegisterWorker + Heartbeat carry the
membership_version that drives elastic mesh re-formation.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.membership import Membership
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = default_logger(__name__)


class MasterServicer:
    def __init__(
        self,
        dispatcher: TaskDispatcher,
        membership: Membership,
        evaluation_service: Optional[EvaluationService] = None,
        wait_backoff_s: float = 2.0,
        summary_service=None,
    ):
        self._dispatcher = dispatcher
        self._membership = membership
        self._evaluation = evaluation_service
        self._summary = summary_service
        self._wait_backoff_s = wait_backoff_s
        self._loss_lock = threading.Lock()
        self._loss_sum = 0.0                # guarded_by: _loss_lock
        self._loss_count = 0                # guarded_by: _loss_lock
        # control-plane flags: mutated by gRPC handler threads (Heartbeat)
        # AND master-side callers (request_checkpoint from the resize
        # quiesce) — the old lock-free set.add/discard raced (edl-lint
        # EDL101 find); worker ids that should checkpoint
        self._ctrl_lock = threading.Lock()
        self._checkpoint_requested = set()  # guarded_by: _ctrl_lock
        self._lr_override = 0.0             # 0 = no master-pushed LR
        self._shutdown = False

    # ------------------------------------------------------------------ #
    # rpc handlers (name-matched by proto/service.py)

    def RegisterWorker(self, request, context):
        info = self._membership.register(
            request.worker_name, request.preferred_id_plus_one - 1
        )
        return pb.RegisterWorkerResponse(
            worker_id=info.worker_id,
            membership_version=self._membership.version,
            num_workers=self._membership.alive_count(),
        )

    def GetTask(self, request, context):
        if self._dispatcher.finished():
            return pb.GetTaskResponse(job_done=True)
        task = self._dispatcher.get(request.worker_id)
        if task is None:
            return pb.GetTaskResponse(
                task=pb.Task(type=pb.WAIT),
                backoff_seconds=self._wait_backoff_s,
                job_done=self._dispatcher.finished(),
            )
        return pb.GetTaskResponse(task=task.to_proto())

    def ReportTaskResult(self, request, context):
        accepted = self._dispatcher.report(
            request.task_id,
            request.worker_id,
            request.success,
            request.err_message,
            preempted=request.preempted,
            records_processed=request.records_processed,
        )
        if accepted and request.loss_count:
            # stale/duplicate reports must not skew the job's mean loss
            with self._loss_lock:
                self._loss_sum += request.loss_sum
                self._loss_count += request.loss_count
            if self._summary is not None:
                self._summary.on_task_report(
                    request.model_version, request.loss_sum, request.loss_count,
                    step_time_sum=request.step_time_sum,
                    step_count=request.step_count,
                )
        if accepted and request.success and self._evaluation is not None:
            # model_version is the worker's minibatch-step counter — the
            # reference's evaluation_steps unit (round-3 fix: this used to
            # count completed *tasks*, ~64x coarser at default task sizes)
            self._evaluation.maybe_trigger(request.model_version)
        return pb.ReportTaskResultResponse(accepted=accepted)

    def ReportEvaluationMetrics(self, request, context):
        if self._evaluation is not None:
            states = {
                s.name: np.frombuffer(s.data, np.float32) for s in request.states
            }
            self._evaluation.report_metrics(
                request.eval_job_id, request.task_id, states
            )
        return pb.ReportEvaluationMetricsResponse()

    def Heartbeat(self, request, context):
        known = self._membership.heartbeat(request.worker_id, request.model_version)
        with self._ctrl_lock:
            # one atomic test-and-clear: the flag is one-shot, and two
            # concurrent heartbeats from a relaunching worker must not both
            # consume (or both miss) the same request
            should_ckpt = request.worker_id in self._checkpoint_requested
            self._checkpoint_requested.discard(request.worker_id)
        return pb.HeartbeatResponse(
            membership_version=self._membership.version,
            num_workers=self._membership.alive_count(),
            should_checkpoint=should_ckpt,
            shutdown=self._shutdown or not known,
            job_done=self._dispatcher.finished(),
            learning_rate=self._lr_override,
        )

    def set_learning_rate(self, lr: float) -> None:
        """Master-side LR override, delivered to every worker on its next
        heartbeat (job callbacks — ReduceLROnPlateau — call this)."""
        self._lr_override = float(lr)

    def GetJobStatus(self, request, context):
        counts = self._dispatcher.counts()
        resp = pb.JobStatusResponse(
            job_done=self._dispatcher.finished(),
            finished_training_tasks=counts["finished_training"],
            pending_tasks=counts["todo"],
            doing_tasks=counts["doing"],
            epoch=counts["epoch"],
            membership_version=self._membership.version,
        )
        if self._evaluation is not None:
            for k, v in self._evaluation.latest_results().items():
                resp.eval_metrics[k] = v
        return resp

    # ------------------------------------------------------------------ #

    def request_checkpoint(self, worker_id: int) -> None:
        with self._ctrl_lock:
            self._checkpoint_requested.add(worker_id)

    def request_shutdown(self) -> None:
        self._shutdown = True

    def mean_training_loss(self) -> Optional[float]:
        with self._loss_lock:
            if not self._loss_count:
                return None
            return self._loss_sum / self._loss_count
