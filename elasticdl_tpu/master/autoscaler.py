"""Closed-loop autoscaler: health signals drive rescale decisions.

Every sensor this needs already exists — the robust-z straggler scorer
(PR 6) with its pluggable hook, the per-step phase profiler saying WHY a
worker is slow (PR 8), the declarative alert engine whose `add_hook` was
explicitly left as "ROADMAP 3's autoscaler seam" (PR 11), and the
goodput ledger pricing every wasted second (PR 12) — yet every rescale
was still human-initiated, so a confirmed straggler degraded the whole
fleet until someone noticed. This module closes the observe→decide loop
(ROADMAP 3; elastic multi-tenant scheduling, 1909.11985, treats
utilization-driven world-size adjustment as the entire point of
elasticity; ElasWave, 2510.00606, argues the rescale decision must be
native to the training system, not bolted on by an operator):

- **Signals** (subscription, never polling the sensors' internals):
  `ClusterHealth.add_hook` delivers straggler ONSETS; `AlertEngine
  .add_hook` delivers `dispatcher_backlog_per_worker` (the grow signal)
  and `fleet_data_wait_dominant` (the shrink signal: an input-bound
  fleet gets nothing from more workers) onsets. Hooks only RECORD —
  decisions happen in `evaluate()`, on the master's existing wait-poll
  cadence, single-threaded like the rest of the control loop.

- **Actions**, through a pluggable target (`bind_target`): `evict` a
  confirmed straggler by shrinking past it — drain-first via the
  existing preempt path (the heartbeat `evict` bit for plain workers;
  the quiesce-checkpoint resize path for cohorts) so its in-flight
  records retire under a drain checkpoint instead of re-training —
  `grow` when backlog-per-worker sustains above threshold, `shrink`
  when the fleet phase profile says data_wait dominates.

- **Robust by construction**:
  * a COST MODEL gates every action: never rescale unless the projected
    goodput gain over `horizon_s` exceeds the projected rescale cost
    (seeded from ``bench.py rescale``'s own `time_to_recovery_s` via
    `--autoscale_rescale_cost_s`, then updated online from the process
    manager's observed re-formation durations);
  * a COOLDOWN window plus signal HOLD (hysteresis) prevents flapping:
    a signal must persist `hold_s` before it is acted on, and actions
    are at least `cooldown_s` apart;
  * min/max world bounds and a per-job ACTION BUDGET cap blast radius —
    at most ONE action per evaluate() pass, ever;
  * every decision — including every SUPPRESSED decision, with its
    reason — is journaled as an ``autoscale`` record and replayed at
    master takeover (journal.AutoscaleState), so a restarted master
    inherits cooldown/budget state instead of immediately re-firing;
    applied decisions are durable BEFORE the action runs (the same
    durable-before-announce ordering as world_version commits);
  * NO DATA means HOLD: when the fleet series go dark (all workers
    churning mid-poll) the rules carry alerts forward and this engine
    takes no action — absence of telemetry is never read as health.

- **Observability**: each action emits an `autoscale.<kind>` trace
  span, `edl_autoscale_*` metrics, and a flight-ring context record;
  suppressions are edge-triggered `autoscale.suppressed` events (one
  per (kind, reason) transition, not one per poll).

Direct `ProcessManager` resize/evict calls outside this module and the
client entry points are flagged by edl-lint **EDL501**
(`rescale-action-outside-policy`): ad-hoc code paths must not bypass
cooldown and journaling.

Stdlib-only and jax-free like the rest of the master's control plane.
See docs/elasticity.md ("Closed-loop autoscaling").
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.journal import AutoscaleState
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

#: action kinds (bounded vocabulary; journal + metric label values)
KINDS = ("evict", "grow", "shrink")

#: suppression reasons (bounded vocabulary; journal + metric label
#: values — every suppressed decision carries exactly one of these)
SUPPRESS_REASONS = (
    "no_target", "unsupported", "cooldown", "budget_exhausted",
    "world_at_min", "world_at_max", "cost_gate", "conflicting_signals",
    "action_failed", "damped", "reversal_hold",
)

#: the two alert rules this engine subscribes to (observability/alerts.py
#: default rule set; a custom --alert_rules file keeps the loop alive by
#: keeping these names)
GROW_RULE = "dispatcher_backlog_per_worker"
SHRINK_RULE = "fleet_data_wait_dominant"

_reg = default_registry()
_AS_ACTIONS = _reg.counter(
    "edl_autoscale_actions_total",
    "closed-loop rescale actions applied", labels=("kind",))
_AS_SUPPRESSED = _reg.counter(
    "edl_autoscale_suppressed_total",
    "autoscale decisions suppressed (edge-triggered per (kind, reason))",
    labels=("reason",))
_AS_BUDGET = _reg.gauge(
    "edl_autoscale_budget_remaining",
    "rescale actions left in this job's autoscale budget")
_AS_COOLDOWN = _reg.gauge(
    "edl_autoscale_cooldown_active",
    "1 while the post-action cooldown window is open")
_AS_PENDING = _reg.gauge(
    "edl_autoscale_pending_signals",
    "signals recorded by the hooks, not yet decided")
_AS_REVERSALS = _reg.counter(
    "edl_autoscale_reversals_total",
    "applied grow->shrink or shrink->grow reversals within one cost "
    "horizon — the oscillation count a noisy signal produces")


class CostModel:
    """Projected-cost gate for rescale decisions.

    The unit is WORKER-SECONDS of goodput: a rescale costs every worker
    in the world roughly `rescale_cost_s` of non-training time (settle +
    handoff + compile — exactly what `bench.py rescale` measures as
    `time_to_recovery_s`, which seeds the initial estimate via
    `--autoscale_rescale_cost_s`); an action's projected gain is the
    goodput it recovers per second, accrued over `horizon_s`. The
    estimate is updated online from observed re-formation durations
    (ProcessManager's reform timer feeds `observe_recovery`) with an
    EWMA, so a fleet whose compiles are warm gates cheaper than one
    paying cold recompiles. Thread-safe (the reform watcher thread
    observes, the wait loop reads)."""

    def __init__(self, rescale_cost_s: float = 10.0,
                 horizon_s: float = 300.0, ewma: float = 0.5):
        self._lock = threading.Lock()
        self._cost_s = max(0.001, float(rescale_cost_s))  # guarded_by: _lock
        self._observed = 0                                # guarded_by: _lock
        self.horizon_s = max(1.0, float(horizon_s))
        self._ewma = min(1.0, max(0.0, float(ewma)))

    @property
    def rescale_cost_s(self) -> float:
        with self._lock:
            return self._cost_s

    @property
    def observed_recoveries(self) -> int:
        with self._lock:
            return self._observed

    def observe_recovery(self, seconds: float) -> None:
        """Feed one measured re-formation duration (never raises)."""
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            return
        if seconds <= 0:
            return
        with self._lock:
            self._observed += 1
            self._cost_s = (
                (1.0 - self._ewma) * self._cost_s + self._ewma * seconds
            )

    # ------------------------------------------------------------------ #
    # per-kind gain projections (worker-seconds over the horizon)

    def project(self, kind: str, world: int, signal: Dict) -> Dict[str, float]:
        """{'gain_s', 'cost_s'} for one candidate action. The models are
        deliberately first-order — the gate's job is to refuse rescales
        whose recovery bill exceeds what they can plausibly recover, not
        to be a scheduler:

        - evict: a synchronous fleet runs at the straggler's pace, so
          the whole world recovers `slowdown_frac` of its wall —
          gain = slowdown_frac * world * horizon;
        - grow: the sustained backlog guarantees the added worker a full
          horizon of work — gain = horizon;
        - shrink: an input-bound worker's wall was mostly data_wait —
          the freed chip-seconds are gain = data_wait_frac * horizon.

        Cost is always `rescale_cost_s` paid by every surviving worker.
        """
        cost_unit = self.rescale_cost_s
        world = max(1, int(world))
        if kind == "evict":
            p50 = float(signal.get("step_time_p50_s") or 0.0)
            med = float(signal.get("median_step_time_s") or 0.0)
            slowdown = max(0.0, (p50 - med) / p50) if p50 > 0 else 0.0
            return {
                "gain_s": round(slowdown * world * self.horizon_s, 3),
                "cost_s": round(cost_unit * world, 3),
            }
        if kind == "grow":
            return {
                "gain_s": round(self.horizon_s, 3),
                "cost_s": round(cost_unit * world, 3),
            }
        if kind == "shrink":
            frac = float(signal.get("value") or 0.0)
            return {
                "gain_s": round(min(1.0, max(0.0, frac)) * self.horizon_s, 3),
                "cost_s": round(cost_unit * max(1, world - 1), 3),
            }
        return {"gain_s": 0.0, "cost_s": float("inf")}


class ProcessManagerTarget:
    """Action adapter over the local ProcessManager (client/local.py
    wires it; only the launcher owns the manager).

    Eviction semantics by mode:

    - plain workers (evaluation/prediction fleets): the SERVICER sets the
      heartbeat `evict` bit, the worker drains through its existing
      preempt path (drain checkpoint + preempted report → the remainder
      requeues FRONT, retry-free, like a death) and exits EX_TEMPFAIL;
      the manager's `evict_worker` marks it never-relaunch so the exit
      retires the slot instead of respawning it.
    - cohorts: one member is one slot of an all-or-nothing SPMD world,
      so eviction IS a drain-first shrink — `remove_worker()` rides the
      planned-resize path (quiesce → checkpoint → teardown → re-form at
      N-1). In the local manager every slot respawns on this host, so
      which slot leaves is immaterial; a multi-host instance manager
      maps the eviction to the straggler's host instead.
    """

    def __init__(self, manager, servicer=None, membership=None):
        self._manager = manager
        self._servicer = servicer
        self._membership = membership

    def rebind(self, servicer=None, membership=None) -> None:
        """Adopt a restarted master's servicer/membership (the manager
        itself survives master restarts — client/local.py rebinds)."""
        if servicer is not None:
            self._servicer = servicer
        if membership is not None:
            self._membership = membership

    def world_size(self) -> int:
        if self._manager.cfg.num_processes > 1:
            return self._manager.pending_size() or self._manager.cohort_size
        if self._membership is not None:
            return self._membership.alive_count()
        return self._manager.cfg.num_workers

    def _plain_training(self) -> bool:
        from elasticdl_tpu.common.constants import JobType

        cfg = self._manager.cfg
        return cfg.num_processes <= 1 and cfg.job_type in (
            JobType.TRAINING_ONLY, JobType.TRAINING_WITH_EVALUATION,
        )

    def supports(self, kind: str) -> bool:
        """Capability probe the policy consults BEFORE spending budget/
        cooldown: a structurally impossible action (growing a plain
        TRAINING fleet — independent replicas with no gradient exchange,
        the same rule ProcessManager.add_worker enforces) must suppress
        as `unsupported`, not journal an applied decision that always
        fails and burns the budget the fleet may later need for a
        legitimate eviction."""
        if kind == "grow":
            return not self._plain_training()
        return True

    def grow(self) -> bool:
        self._manager.add_worker()
        return True

    def shrink(self) -> bool:
        if self._manager.cfg.num_processes > 1:
            self._manager.remove_worker()
            return True
        # plain fleet (evaluation/prediction workers): shrink IS an
        # eviction of the most recently added capacity, through the same
        # drain handshake — remove_worker() is cohort-only by contract
        if self._membership is None:
            return False
        alive = [
            w.worker_id for w in self._membership.alive_workers()
            if w.led_by is None
        ]
        if not alive:
            return False
        return self.evict(max(alive))

    def evict(self, worker_id: int, worker_name: str = "") -> bool:
        if self._manager.cfg.num_processes > 1 or "#p" in worker_name:
            # cohort member: drain-first shrink (the resize quiesce IS
            # the drain — a checkpoint lands before teardown)
            self._manager.remove_worker()
            return True
        if self._servicer is not None:
            # the wire half of the drain handshake: the worker's next
            # heartbeat carries evict=True and it drains + exits
            self._servicer.request_evict(worker_id)
        return self._manager.evict_worker(worker_id)


class K8sInstanceTarget:
    """Action adapter over the master-owned K8sInstanceManager (the
    instance_manager='k8s' flavor — master/main.py wires it at start).
    Pod deletion already drives lease recovery identically to eviction;
    the heartbeat evict bit still runs first so the pod drains before
    the grace period kills it."""

    def __init__(self, manager, servicer=None, membership=None):
        self._manager = manager
        self._servicer = servicer
        self._membership = membership

    def world_size(self) -> int:
        if self._membership is not None:
            return self._membership.alive_count()
        return self._manager.cfg.num_workers

    def supports(self, kind: str) -> bool:
        """k8s pods are plain workers: growing a TRAINING fleet would
        train divergent replicas (K8sInstanceManager.add_worker enforces
        it) — suppress as `unsupported` instead of burning budget."""
        if kind == "grow":
            from elasticdl_tpu.common.constants import JobType

            return self._manager.cfg.job_type not in (
                JobType.TRAINING_ONLY, JobType.TRAINING_WITH_EVALUATION,
            )
        return True

    def grow(self) -> bool:
        self._manager.add_worker()
        return True

    def shrink(self) -> bool:
        # no per-worker signal to pick from: shed the highest worker id
        # (the most recently added capacity)
        if self._membership is None:
            return False
        alive = [w.worker_id for w in self._membership.alive_workers()]
        if not alive:
            return False
        wid = max(alive)
        if self._servicer is not None:
            self._servicer.request_evict(wid)
        self._manager.remove_worker(wid)
        return True

    def evict(self, worker_id: int, worker_name: str = "") -> bool:
        if self._servicer is not None:
            self._servicer.request_evict(worker_id)
        self._manager.remove_worker(worker_id)
        return True


class Autoscaler:
    """The policy engine. One instance per master; `evaluate()` runs on
    the wait-poll cadence and never raises."""

    #: deadband as a fraction of the rule threshold: with damping on, a
    #: smoothed grow/shrink signal must clear its threshold by this
    #: margin before it is actionable — hovering AT the threshold (the
    #: noisy-signal thrash mode the fleet soak reproduces) stays held
    DAMPING_DEADBAND = 0.1

    def __init__(
        self,
        *,
        journal=None,
        cost_model: Optional[CostModel] = None,
        min_world: int = 1,
        max_world: int = 0,          # 0 = unbounded
        cooldown_s: float = 120.0,
        hold_s: float = 30.0,
        action_budget: int = 8,
        damping: float = 0.0,
        reversal_hold_s: float = 0.0,
        clock: Callable[[], float] = time.time,
    ):
        self._journal = journal
        self.cost = cost_model or CostModel()
        self.min_world = max(1, int(min_world))
        self.max_world = int(max_world)
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.hold_s = max(0.0, float(hold_s))
        self.action_budget = max(0, int(action_budget))
        # signal damping (--autoscale_damping): EWMA smoothing factor in
        # [0, 1) — 0 disables. Grow/shrink decide on the SMOOTHED alert
        # value, and only when it clears the rule threshold by the
        # deadband margin, so one noisy sample cannot flip the loop.
        self.damping = min(0.999, max(0.0, float(damping)))
        # anti-thrash (--autoscale_reversal_hold_s): a grow→shrink or
        # shrink→grow candidate inside this window of the LAST applied
        # opposite action suppresses as `reversal_hold` — the fleet it
        # would resize is still paying for the previous resize
        self.reversal_hold_s = max(0.0, float(reversal_hold_s))
        # wall clock ON PURPOSE (not monotonic): last_action_ts is
        # journaled and must survive a master restart — a monotonic
        # stamp from a dead process is meaningless to its successor
        self._clock = clock
        self._lock = threading.Lock()
        # pending signals recorded by the hooks; decided by evaluate()
        self._stragglers: Dict[int, Dict] = {}        # guarded_by: _lock
        self._grow_signal: Optional[Dict] = None      # guarded_by: _lock
        self._shrink_signal: Optional[Dict] = None    # guarded_by: _lock
        # replayed (or fresh) durable state: cooldown + budget survive
        # master takeover via the journal's autoscale records
        snap = (
            journal.autoscale_snapshot() if journal is not None else None
        )
        self._state = snap if snap is not None else AutoscaleState()
        if snap is not None and (snap.actions_applied or snap.records):
            logger.warning(
                "autoscaler state restored from control journal: %d "
                "action(s) applied (budget %d), last action ts %.0f — "
                "cooldown inherited",
                snap.actions_applied, self.action_budget,
                snap.last_action_ts,
            )
        # edge-trigger state for suppressed-decision journaling: one
        # record per (kind, reason) TRANSITION, not one per poll
        self._last_suppressed: Dict[str, str] = {}    # guarded_by: _lock
        self._last_decision: Optional[Dict] = None    # guarded_by: _lock
        # EWMA of each rule's live alert value (damping > 0 only); decays
        # toward 0 while the alert is inactive         # guarded_by: _lock
        self._smoothed: Dict[str, float] = {}
        # last APPLIED grow/shrink: (kind, ts) — reversal detection.
        # In-memory only: a restarted master starts direction-blind,
        # which errs toward counting/suppressing less, never more.
        self._last_resize: Optional[tuple] = None     # guarded_by: _lock
        self._reversals = 0                           # guarded_by: _lock
        self._target = None
        self._health = None
        self._alerts = None
        _AS_BUDGET.set(max(0, self.action_budget - self._state.actions_applied))

    # ------------------------------------------------------------------ #
    # wiring

    def subscribe(self, health=None, alerts=None) -> "Autoscaler":
        """Attach to the two decision seams. Hooks only record — the
        scorer/engine must survive a policy bug, and a decision needs
        the full fleet picture evaluate() assembles anyway."""
        if health is not None:
            self._health = health
            health.add_hook(self._on_straggler)
        if alerts is not None:
            self._alerts = alerts
            alerts.add_hook(self._on_alert)
        return self

    def bind_target(self, target) -> None:
        """Attach the action surface (ProcessManagerTarget /
        K8sInstanceTarget / a test double). Until one is bound every
        decision suppresses with `no_target` — journaled, so a
        mis-wired deployment is visible in the record stream."""
        self._target = target

    # ------------------------------------------------------------------ #
    # signal intake (hook threads; record only, never act)

    def _on_straggler(self, info: Dict) -> None:
        wid = int(info.get("worker_id", -1))
        if wid < 0:
            return
        with self._lock:
            sig = dict(info)
            sig["first_seen"] = self._clock()
            self._stragglers[wid] = sig
        logger.info(
            "autoscaler: straggler signal recorded for worker %d "
            "(hold %.0fs before action)", wid, self.hold_s,
        )

    def _on_alert(self, info: Dict) -> None:
        rule = str(info.get("rule", ""))
        if rule not in (GROW_RULE, SHRINK_RULE):
            return
        with self._lock:
            sig = dict(info)
            sig["first_seen"] = self._clock()
            if rule == GROW_RULE:
                self._grow_signal = sig
            else:
                self._shrink_signal = sig
        logger.info("autoscaler: %s signal recorded (%s)", rule,
                    "grow" if rule == GROW_RULE else "shrink")

    # ------------------------------------------------------------------ #
    # the decision pass

    def evaluate(self, now: Optional[float] = None) -> Optional[Dict]:
        """One decision pass; returns the applied decision (or None).
        Never raises — the master's wait loop calls this
        unconditionally."""
        try:
            return self._evaluate(now)
        except Exception:
            logger.exception("autoscale evaluation failed; holding")
            return None

    def _evaluate(self, now: Optional[float] = None) -> Optional[Dict]:
        now = self._clock() if now is None else now
        with self._lock:
            stragglers = dict(self._stragglers)
            grow = self._grow_signal
            shrink = self._shrink_signal
        # re-validate against the live sensors: a signal whose condition
        # cleared (or whose sensor went dark — the carried-forward/no-data
        # contract) is dropped or held, never acted on stale
        if self._health is not None and stragglers:
            snap = self._health.snapshot()
            flagged = {
                int(i.get("worker_id", -1)) for i in snap.get("stragglers", ())
            }
            for wid in list(stragglers):
                if wid not in flagged:
                    with self._lock:
                        self._stragglers.pop(wid, None)
                        if not self._stragglers:
                            # a NEW straggler incident later must journal
                            # its own suppressions (edge-trigger resets
                            # with the signal)
                            self._last_suppressed.pop("evict", None)
                    stragglers.pop(wid, None)
                    logger.info(
                        "autoscaler: straggler signal for worker %d "
                        "cleared before action", wid,
                    )
        if self._alerts is not None:
            active_alerts = self._alerts.active()
            active = {a.get("rule") for a in active_alerts}
            if self.damping > 0:
                # EWMA over the LIVE alert value each poll (an inactive
                # alert contributes 0, so the smoothed series decays
                # instead of freezing at its last noisy spike)
                vals = {
                    str(a.get("rule")): float(a.get("value") or 0.0)
                    for a in active_alerts
                    if a.get("rule") in (GROW_RULE, SHRINK_RULE)
                }
                alpha = 1.0 - self.damping
                with self._lock:
                    for rule in (GROW_RULE, SHRINK_RULE):
                        v = vals.get(rule, 0.0)
                        # decay up from a 0 baseline on first sight, so
                        # damping also blunts signal ONSET — seeding with
                        # the first raw sample would let a single spike
                        # through undamped
                        prev = self._smoothed.get(rule, 0.0)
                        self._smoothed[rule] = (
                            alpha * v + (1.0 - alpha) * prev
                        )
            if grow is not None and GROW_RULE not in active:
                with self._lock:
                    self._grow_signal = None
                    self._last_suppressed.pop("grow", None)
                grow = None
            if shrink is not None and SHRINK_RULE not in active:
                with self._lock:
                    self._shrink_signal = None
                    self._last_suppressed.pop("shrink", None)
                shrink = None
        _AS_PENDING.set(
            len(stragglers) + (1 if grow else 0) + (1 if shrink else 0))
        _AS_COOLDOWN.set(1 if self._in_cooldown(now) else 0)
        if grow is not None and shrink is not None:
            # the fleet cannot be simultaneously short of workers and
            # input-bound; acting on either would flap — suppress both
            # and wait for one to clear
            self._suppress("grow", grow, "conflicting_signals", now)
            self._suppress("shrink", shrink, "conflicting_signals", now)
            grow = shrink = None
        # priority: evict (a confirmed straggler degrades everyone) >
        # grow > shrink; at most ONE action per pass (blast radius)
        candidates = []
        for wid, sig in sorted(stragglers.items()):
            candidates.append(("evict", sig))
        if grow is not None:
            candidates.append(("grow", grow))
        if shrink is not None:
            candidates.append(("shrink", shrink))
        for kind, sig in candidates:
            if now - float(sig.get("first_seen") or now) < self.hold_s:
                continue   # hysteresis hold: not yet a decision
            decision = self._decide(kind, sig, now)
            if decision is not None:
                return decision
        return None

    def _in_cooldown(self, now: float) -> bool:
        last = self._state.last_action_ts
        # wall-clock delta ON PURPOSE: last_action_ts is journal-replayed
        # state from a possibly-dead process, the one clock restarts
        # share — edl-lint: disable=EDL406
        return bool(last > 0 and now - last < self.cooldown_s)

    def _decide(self, kind: str, signal: Dict, now: float) -> Optional[Dict]:
        """Run one candidate through the gates; apply or suppress.
        Returns the applied decision dict, or None when suppressed."""
        target = self._target
        if target is None:
            self._suppress(kind, signal, "no_target", now)
            return None
        supports = getattr(target, "supports", None)
        if supports is not None and not supports(kind):
            # structurally impossible on this fleet shape (e.g. growing
            # a plain training job): suppress BEFORE the budget/cooldown
            # spend — an applied-then-always-failing decision would burn
            # the whole action budget against a sustained alert
            self._suppress(kind, signal, "unsupported", now)
            return None
        if self.damping > 0 and kind in ("grow", "shrink"):
            rule = GROW_RULE if kind == "grow" else SHRINK_RULE
            with self._lock:
                smoothed = self._smoothed.get(rule)
            threshold = float(signal.get("threshold") or 0.0)
            op = str(signal.get("op") or ">")
            margin = abs(threshold) * self.DAMPING_DEADBAND
            breached = smoothed is not None and (
                smoothed <= threshold - margin if op in ("<", "<=")
                else smoothed >= threshold + margin
            )
            if not breached:
                self._suppress(
                    kind, signal, "damped", now,
                    smoothed=round(smoothed or 0.0, 3),
                )
                return None
        if self.reversal_hold_s > 0 and kind in ("grow", "shrink"):
            with self._lock:
                last = self._last_resize
            if (last is not None and last[0] != kind
                    and now - last[1] < self.reversal_hold_s):
                self._suppress(
                    kind, signal, "reversal_hold", now,
                    prior_kind=last[0], prior_ts=round(last[1], 3),
                )
                return None
        world = max(1, int(target.world_size()))
        new_world = world + (1 if kind == "grow" else -1)
        if kind in ("evict", "shrink") and new_world < self.min_world:
            self._suppress(kind, signal, "world_at_min", now, world=world)
            return None
        if kind == "grow" and self.max_world and new_world > self.max_world:
            self._suppress(kind, signal, "world_at_max", now, world=world)
            return None
        if self._state.actions_applied >= self.action_budget:
            self._suppress(kind, signal, "budget_exhausted", now, world=world)
            return None
        if self._in_cooldown(now):
            self._suppress(kind, signal, "cooldown", now, world=world)
            return None
        proj = self.cost.project(kind, world, signal)
        if proj["gain_s"] <= proj["cost_s"]:
            self._suppress(
                kind, signal, "cost_gate", now, world=world, **proj)
            return None
        return self._apply(kind, signal, now, world, new_world, proj)

    # ------------------------------------------------------------------ #
    # outcomes

    def _signal_fields(self, kind: str, signal: Dict) -> Dict:
        out: Dict = {"kind": kind}
        if kind == "evict":
            out["worker_id"] = int(signal.get("worker_id", -1))
            out["worker_name"] = str(signal.get("worker_name", ""))
            out["reason"] = (
                f"straggler score {signal.get('score')} "
                f"(p50 {signal.get('step_time_p50_s')}s vs median "
                f"{signal.get('median_step_time_s')}s)"
            )
        else:
            out["reason"] = (
                f"alert {signal.get('rule')} value {signal.get('value')} "
                f"{signal.get('op', '>')} threshold "
                f"{signal.get('threshold')}"
            )
        return out

    def _journal_append(self, rec: Dict, await_commit: bool) -> None:
        if self._journal is None:
            return
        commit = self._journal.append("autoscale", **rec)
        if await_commit:
            # durable-before-action: the decision must survive a crash
            # landing mid-action, or the successor would re-fire it
            commit.wait()

    def _suppress(self, kind: str, signal: Dict, reason: str, now: float,
                  **extra) -> None:
        """Journal + count a suppressed decision — edge-triggered per
        (kind, reason): the record stream must say WHY the loop held,
        without one line per poll while it holds."""
        with self._lock:
            if self._last_suppressed.get(kind) == reason:
                return
            self._last_suppressed[kind] = reason
        info = self._signal_fields(kind, signal)
        info.update(
            decision="suppressed", suppress_reason=reason,
            ts=round(now, 3), **extra,
        )
        # reason values come from the bounded SUPPRESS_REASONS
        # vocabulary at every call site: edl-lint: disable=EDL405
        _AS_SUPPRESSED.inc(reason=reason)
        with self._lock:
            self._state.records += 1
            self._last_decision = dict(info)
        try:
            self._journal_append(info, await_commit=False)
        except Exception:
            logger.exception("autoscale suppressed-decision journal failed")
        tracing.event("autoscale.suppressed", **{
            k: v for k, v in info.items() if k != "decision"
        })
        logger.info(
            "autoscale %s suppressed (%s): %s",
            kind, reason, info.get("reason", ""),
        )

    def _apply(self, kind: str, signal: Dict, now: float, world: int,
               new_world: int, proj: Dict) -> Optional[Dict]:
        info = self._signal_fields(kind, signal)
        info.update(
            decision="applied", ts=round(now, 3), world=world,
            target_world=new_world, **proj,
        )
        with tracing.span(f"autoscale.{kind}", **{
            k: v for k, v in info.items()
            if k in ("worker_id", "world", "target_world", "gain_s", "cost_s")
        }) as span:
            # journal FIRST, fsync-awaited: a crash between here and the
            # action replays the decision as taken (cooldown holds, no
            # double-fire) — the conservative direction, mirroring the
            # world_version durable-before-announce ordering
            try:
                self._journal_append(info, await_commit=True)
            except Exception:
                logger.exception(
                    "autoscale decision could not be journaled; action "
                    "ABORTED (an unjournaled rescale would re-fire after "
                    "takeover)")
                span.set(outcome="journal_failed")
                return None
            reversal = False
            with self._lock:
                self._state.actions_applied += 1
                self._state.last_action_ts = max(
                    self._state.last_action_ts, now)
                self._state.by_kind[kind] = (
                    self._state.by_kind.get(kind, 0) + 1)
                self._state.records += 1
                self._last_decision = dict(info)
                self._last_suppressed.pop(kind, None)
                if kind in ("grow", "shrink"):
                    last = self._last_resize
                    if (last is not None and last[0] != kind
                            and now - last[1] <= self.cost.horizon_s):
                        reversal = True
                        self._reversals += 1
                    self._last_resize = (kind, now)
                if kind == "evict":
                    self._stragglers.pop(info.get("worker_id"), None)
                elif kind == "grow":
                    self._grow_signal = None
                else:
                    self._shrink_signal = None
            if reversal:
                _AS_REVERSALS.inc()
                span.set(reversal=True)
                logger.warning(
                    "autoscale REVERSAL: %s within one horizon of the "
                    "opposite action — the loop is oscillating "
                    "(consider --autoscale_damping / "
                    "--autoscale_reversal_hold_s)", kind,
                )
            ok = False
            try:
                if kind == "evict":
                    ok = bool(self._target.evict(
                        info.get("worker_id", -1),
                        info.get("worker_name", ""),
                    ))
                elif kind == "grow":
                    ok = bool(self._target.grow())
                else:
                    ok = bool(self._target.shrink())
            except Exception:
                logger.exception("autoscale %s action failed", kind)
            span.set(outcome="ok" if ok else "action_failed")
        # kind values come from the bounded KINDS vocabulary:
        # edl-lint: disable=EDL405
        _AS_ACTIONS.inc(kind=kind)
        _AS_BUDGET.set(max(0, self.action_budget - self._state.actions_applied))
        _AS_COOLDOWN.set(1)
        if not ok:
            # the decision stands (cooldown holds — hammering a failing
            # target would be its own flap mode); the failure is its own
            # journal record for the postmortem. The SIGNAL is re-armed:
            # hooks fire only at ONSET, and a continuously-flagged
            # straggler (or still-active alert) produces no new one — a
            # transient target failure must retry after the cooldown,
            # not strand the straggler for the rest of the job. The next
            # evaluate re-validates against the live sensor, so a signal
            # that cleared meanwhile still drops.
            with self._lock:
                if kind == "evict":
                    self._stragglers.setdefault(
                        int(info.get("worker_id", -1)), dict(signal))
                elif kind == "grow":
                    if self._grow_signal is None:
                        self._grow_signal = dict(signal)
                elif self._shrink_signal is None:
                    self._shrink_signal = dict(signal)
            self._suppress(kind, signal, "action_failed", now, world=world)
        # context to the flight ring: the black box must carry what the
        # fleet looked like at the moment the loop acted
        try:
            from elasticdl_tpu.observability import flight as flight_lib

            flight_lib.get_recorder().record(
                "autoscale", kind, **{
                    k: v for k, v in info.items()
                    if k not in ("decision", "kind")
                },
            )
        except Exception:
            logger.exception("autoscale flight record failed")
        logger.warning(
            "AUTOSCALE %s applied: world %d -> %d (%s; projected gain "
            "%.1fs > cost %.1fs; budget %d/%d)",
            kind, world, new_world, info.get("reason", ""),
            proj["gain_s"], proj["cost_s"],
            self._state.actions_applied, self.action_budget,
        )
        return info

    # ------------------------------------------------------------------ #
    # introspection

    def snapshot(self) -> Dict:
        """Cheap state view (/healthz enrichment + bench artifacts)."""
        now = self._clock()
        with self._lock:
            # copy EVERYTHING mutable inside the lock: the wait loop's
            # _apply mutates by_kind/counters under it, and an HTTP
            # /healthz thread iterating a live dict would race
            actions_applied = self._state.actions_applied
            by_kind = dict(self._state.by_kind)
            records = self._state.records
            reversals = self._reversals
            smoothed = dict(self._smoothed)
            last = dict(self._last_decision) if self._last_decision else None
            pending = (
                len(self._stragglers)
                + (1 if self._grow_signal else 0)
                + (1 if self._shrink_signal else 0)
            )
        return {
            "enabled": self._target is not None,
            "actions_applied": actions_applied,
            "action_budget": self.action_budget,
            "budget_remaining": max(
                0, self.action_budget - actions_applied),
            "by_kind": by_kind,
            "cooldown_s": self.cooldown_s,
            "cooldown_active": self._in_cooldown(now),
            "hold_s": self.hold_s,
            "min_world": self.min_world,
            "max_world": self.max_world,
            "rescale_cost_s": round(self.cost.rescale_cost_s, 3),
            "horizon_s": self.cost.horizon_s,
            "pending_signals": pending,
            "last_decision": last,
            "decision_records": records,
            "damping": self.damping,
            "reversal_hold_s": self.reversal_hold_s,
            "reversals": reversals,
            "smoothed_signals": {
                k: round(v, 4) for k, v in smoothed.items()
            },
        }


def from_config(cfg, journal=None) -> Optional[Autoscaler]:
    """Build the engine from a JobConfig (None when --autoscale is off).
    The caller subscribes and binds the target."""
    if not getattr(cfg, "autoscale", False):
        return None
    return Autoscaler(
        journal=journal,
        cost_model=CostModel(
            rescale_cost_s=cfg.autoscale_rescale_cost_s,
            horizon_s=cfg.autoscale_horizon_s,
        ),
        min_world=cfg.autoscale_min_workers,
        max_world=cfg.autoscale_max_workers,
        cooldown_s=cfg.autoscale_cooldown_s,
        hold_s=cfg.autoscale_hold_s,
        action_budget=cfg.autoscale_actions_max,
        damping=getattr(cfg, "autoscale_damping", 0.0),
        reversal_hold_s=getattr(cfg, "autoscale_reversal_hold_s", 0.0),
    )
