"""Incident flight recorder: a per-process black box for postmortems.

When a worker or master dies today, the evidence dies with it: spans are
sampled to per-process trace.jsonl files (best-effort, possibly torn),
health snapshots are point-in-time, and the last seconds before the death
are reconstructed by hand from N logs. This module gives every process a
bounded in-memory ring that records recent telemetry at FULL fidelity —
spans and events (subscribed from the tracer), structured log lines (a
logging handler), explicit records, and metric deltas — at near-zero
hot-path cost (a deque append under a leaf lock), and dumps it as one
atomic JSON bundle when something goes wrong.

Trigger matrix (docs/observability.md "Flight recorder"):

    unhandled exception    sys.excepthook wrapper -> reason "crash:<type>"
    fault-site crash       faults.add_crash_hook -> reason "fault:<site>"
                           (runs before the injector's os._exit)
    SIGUSR2                operator/offender trigger -> reason "sigusr2"
                           (the straggler hook's offender snapshot rides
                           this: ProcessManager.request_flight_dump)
    /debug/flight          ObservabilityServer endpoint -> reason "http"
                           (dump + the bundle served back)
    straggler onset        the master's ClusterHealth hook dumps the
                           MASTER's ring (reason "straggler:worker-N") and
                           the local launcher SIGUSR2s the offender
    explicit               FlightRecorder.dump(reason) — preemption drains,
                           chaos scenarios, tests
    atexit                 only with EDL_FLIGHT_DUMP_ON_EXIT=1 (a clean
                           exit is not an incident)

Bundle (`flight-<role>-<pid>.json`, written tmp + os.replace so a torn
bundle can only mean the writer itself died mid-incident):

    {"schema": 1, "kind": "flight", "role": ..., "pid": ..., "reason": ...,
     "ts": <wall s>, "world_version": ..., "dump_seq": N,
     "meta": {...configure()-time facts...},
     "records": [ring records, oldest first — tracer-schema spans/events,
                 {"kind": "log", ...} lines, explicit records],
     "metrics": {series: value},            # full registry snapshot
     "metrics_delta": {series: delta},      # vs the previous dump/mark
     "profile": {...step-profiler snapshot...},
     "goodput": {...goodput-ledger snapshot: per-category wall-clock
                 attribution at the moment the box was cut...}}

Everything here is stdlib-only, jax-free, and strictly best-effort: a
full ring, a failed dump, or a missing directory must never take the
process (or a concurrent /metrics scrape) down. The offline correlator
(`python -m elasticdl_tpu.observability.incident <dir>`) merges bundles
from every role into one timeline.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import (
    MetricsRegistry,
    default_registry,
)

logger = default_logger(__name__)

SCHEMA_VERSION = 1
BUNDLE_PREFIX = "flight-"

#: default ring capacity (records); env/config override
RING_DEFAULT = 4096
RING_ENV = "EDL_FLIGHT_RING"
#: env override for the bundle directory ("off" disables dumping)
DIR_ENV = "EDL_FLIGHT_DIR"
#: opt-in: also dump on clean interpreter exit
DUMP_ON_EXIT_ENV = "EDL_FLIGHT_DUMP_ON_EXIT"

_reg = default_registry()
_FL_RECORDS = _reg.counter(
    "edl_flight_records_total", "records appended to the flight ring")
_FL_DUMPS = _reg.counter(
    "edl_flight_dumps_total", "flight bundles dumped", labels=("reason",))
_FL_DUMP_FAILURES = _reg.counter(
    "edl_flight_dump_failures_total", "flight bundle writes that failed")


class FlightRecorder:
    """Bounded ring of recent telemetry + atomic bundle dumps.

    The ring lock is a LEAF lock (nothing inside it acquires anything
    else), so recording from the tracer's emit path, a log handler, or
    the train loop can never participate in a lock-order cycle. Dumps
    snapshot the ring under the lock and do ALL file I/O outside it, so
    a dump in progress never blocks recording or a /metrics scrape.
    """

    def __init__(self, ring: Optional[int] = None, role: str = "",
                 registry: Optional[MetricsRegistry] = None):
        size = ring if ring is not None else _ring_from_env()
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max(16, int(size)))  # guarded_by: _lock
        self._seq = 0                                 # guarded_by: _lock
        self.role = role
        self.dir: Optional[str] = None
        # optional filename disambiguator: several recorders for the SAME
        # role+pid (chaos scenarios run back-to-back in one pytest
        # process) must not clobber each other's bundles
        self.tag = ""
        self._meta: Dict[str, Any] = {}
        self._registry = registry or default_registry()
        self._baseline: Dict[str, float] = {}         # guarded_by: _lock
        self._dump_seq = 0                            # guarded_by: _lock
        self._dump_reasons: List[str] = []            # guarded_by: _lock
        self.last_dump_path: Optional[str] = None
        self._log_handler: Optional[logging.Handler] = None
        self._sink_attached = False

    # ------------------------------------------------------------------ #
    # configuration / subscriptions

    def configure(self, dir: Optional[str] = None, role: Optional[str] = None,
                  tag: Optional[str] = None, **meta: Any) -> "FlightRecorder":
        """(Re)point the recorder: bundle directory (None keeps, "" means
        memory-only — the ring still records, dumps are no-ops), role
        stamp, an optional filename `tag` (bundles become
        flight-<role>-<tag>-<pid>.json — scenario stems that must not
        overwrite each other), and any meta facts worth carrying into
        every bundle."""
        if role is not None:
            self.role = role
        if tag is not None:
            self.tag = tag
        if dir is not None:
            self.dir = dir or None
        if meta:
            self._meta.update(meta)
        return self

    def attach_tracing(self) -> "FlightRecorder":
        """Subscribe to the process tracer: every span/event record lands
        in the ring at full fidelity (the trace.jsonl file sink stays
        sampled/best-effort; the ring is the black box)."""
        if not self._sink_attached:
            tracing.get_tracer().add_sink(self._on_trace_record)
            self._sink_attached = True
        return self

    def detach_tracing(self) -> None:
        if self._sink_attached:
            tracing.get_tracer().remove_sink(self._on_trace_record)
            self._sink_attached = False

    def _on_trace_record(self, rec: dict) -> None:
        # called from Tracer._emit under the tracer lock: the ring append
        # below takes only the leaf ring lock — cheap and cycle-free
        self._append(dict(rec))

    def attach_logging(self, level: int = logging.INFO,
                       logger_name: str = "elasticdl_tpu") -> "FlightRecorder":
        """Capture structured log lines (default: INFO and up from the
        project logger — a healthy run's registrations/restores/task flow
        are exactly the context a postmortem wants around the crash line;
        DEBUG stays out so a verbose run cannot wash the ring) into the
        ring. Idempotent."""
        if self._log_handler is not None:
            return self
        handler = _RingLogHandler(self)
        handler.setLevel(level)
        logging.getLogger(logger_name).addHandler(handler)
        self._log_handler = handler
        return self

    def detach_logging(self, logger_name: str = "elasticdl_tpu") -> None:
        if self._log_handler is not None:
            logging.getLogger(logger_name).removeHandler(self._log_handler)
            self._log_handler = None

    # ------------------------------------------------------------------ #
    # recording

    def record(self, kind: str, name: str, **attrs: Any) -> None:
        """Append one explicit record (ts stamped here)."""
        rec = {"kind": kind, "name": name, "ts": time.time()}
        rec.update(attrs)
        rec.setdefault("role", self.role)
        self._append(rec)

    def _append(self, rec: dict) -> None:
        with self._lock:
            self._seq += 1
            rec.setdefault("seq", self._seq)
            self._ring.append(rec)
        _FL_RECORDS.inc()

    def snapshot(self) -> List[dict]:
        """Ring contents, oldest first (copies of the refs, cheap)."""
        with self._lock:
            return list(self._ring)

    def mark_metrics(self) -> None:
        """Reset the metric-delta baseline (dump() does this implicitly,
        so deltas read "since the last dump")."""
        snap = self._safe_metrics()
        with self._lock:
            self._baseline = snap

    def _safe_metrics(self) -> Dict[str, float]:
        try:
            return self._registry.snapshot()
        except Exception:
            # the bundle must still land without its metrics block:
            # edl-lint: disable=EDL303
            return {}

    # ------------------------------------------------------------------ #
    # dumping

    def bundle(self, reason: str) -> dict:
        """Assemble the bundle dict (no file I/O; /debug/flight serves
        this directly)."""
        metrics = self._safe_metrics()
        with self._lock:
            records = list(self._ring)
            baseline = dict(self._baseline)
            self._baseline = dict(metrics)
            self._dump_seq += 1
            dump_seq = self._dump_seq
            reasons = list(self._dump_reasons)
            self._dump_reasons.append(reason)
        delta = {
            k: round(v - baseline.get(k, 0.0), 9)
            for k, v in metrics.items()
            if v != baseline.get(k, 0.0)
        }
        out = {
            "schema": SCHEMA_VERSION,
            "kind": "flight",
            "role": self.role,
            "pid": os.getpid(),
            "reason": reason,
            "ts": time.time(),
            "world_version": tracing.get_tracer().world_version,
            "dump_seq": dump_seq,
            "prior_dump_reasons": reasons,
            "meta": dict(self._meta),
            "records": records,
            "metrics": metrics,
            "metrics_delta": delta,
        }
        try:
            from elasticdl_tpu.observability import profile as profile_lib

            out["profile"] = profile_lib.get_profiler().snapshot()
        except Exception:
            # the profiler block is advisory; a bundle without it is still
            # a bundle: edl-lint: disable=EDL303
            pass
        try:
            from elasticdl_tpu.observability import goodput as goodput_lib

            # the process's goodput attribution at the moment the box
            # was cut — the per-worker half of the incident's bill
            # (ISSUE 12; the fleet half rides health snapshots)
            out["goodput"] = goodput_lib.get_ledger().snapshot()
        except Exception:
            # advisory, same as the profiler block:
            # edl-lint: disable=EDL303
            pass
        try:
            from elasticdl_tpu.observability import reqtrace

            # retained request diaries (ISSUE 19): the tail-sampled
            # slow/error/degraded calls the incident CLI renders as
            # `slow_calls` stage waterfalls. None when the data plane
            # never ran here — absence means no-data, not a clean tail.
            diaries = reqtrace.get_recorder().bundle_block()
            if diaries is not None:
                out["diaries"] = diaries
        except Exception:
            # advisory, same as the profiler block:
            # edl-lint: disable=EDL303
            pass
        return out

    def dump(self, reason: str, dir: Optional[str] = None,
             bundle: Optional[dict] = None) -> Optional[str]:
        """Write the bundle atomically as flight-<role>-<pid>.json under
        the configured (or given) directory; successive dumps overwrite —
        latest incident wins, prior reasons ride `prior_dump_reasons`.
        NEVER raises; returns the path, or None when disabled/failed.
        `bundle` lets a caller that already assembled one (/debug/flight)
        persist it without a second ring/metrics pass."""
        target_dir = dir or self.dir
        if bundle is None:
            bundle = self.bundle(reason)
        if not target_dir:
            return None
        def slug(s: str) -> str:
            return s.replace("/", "_").replace(" ", "_")

        stem = slug(self.role or "proc")
        if self.tag:
            stem += "-" + slug(self.tag)
        path = os.path.join(
            target_dir, f"{BUNDLE_PREFIX}{stem}-{os.getpid()}.json"
        )
        tmp = path + ".tmp"
        try:
            os.makedirs(target_dir, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=repr)
                f.write("\n")
                f.flush()
                # a crash bundle exists precisely because the process is
                # dying: make it durable before the rename
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception:
            _FL_DUMP_FAILURES.inc()
            logger.exception("flight bundle dump (%s) failed", reason)
            return None
        _FL_DUMPS.inc(reason=reason.split(":", 1)[0])
        self.last_dump_path = path
        logger.warning("flight bundle dumped (%s) -> %s", reason, path)
        return path


class _RingLogHandler(logging.Handler):
    """Log capture into the flight ring (formatted message + context)."""

    def __init__(self, recorder: FlightRecorder):
        super().__init__()
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder._append({
                "kind": "log",
                "name": record.name,
                "level": record.levelname,
                "msg": record.getMessage()[:512],
                "ts": record.created,
                "role": self._recorder.role,
            })
        except Exception:
            # log capture must never become a logging failure loop:
            # edl-lint: disable=EDL303
            pass


# ---------------------------------------------------------------------- #
# module-level singleton + trigger installation

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()
_HOOKS_INSTALLED = False


def _ring_from_env() -> int:
    try:
        return int(os.environ.get(RING_ENV, "") or RING_DEFAULT)
    except ValueError:
        return RING_DEFAULT


def get_recorder() -> FlightRecorder:
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def flight_dir_for(cfg) -> Optional[str]:
    """The bundle directory a JobConfig implies: cfg.flight_dir, else
    derived next to the job's other observability artifacts ("off"
    disables; EDL_FLIGHT_DIR overrides either way)."""
    env = os.environ.get(DIR_ENV)
    if env is not None and env.strip():
        return None if env.strip().lower() == "off" else env.strip()
    raw = getattr(cfg, "flight_dir", "") or ""
    if raw.lower() == "off":
        return None
    if raw:
        return raw
    base = getattr(cfg, "summary_dir", "") or getattr(
        cfg, "checkpoint_dir", ""
    )
    return os.path.join(base, "flight") if base else None


def configure_from_config(cfg, role: str) -> FlightRecorder:
    """Entrypoint helper (master/worker/cohort): point the process
    recorder at the job's flight dir, stamp the role, subscribe to the
    tracer and the project log stream."""
    rec = get_recorder()
    ring = getattr(cfg, "flight_ring", 0) or 0
    if ring and rec._ring.maxlen != ring:
        with rec._lock:
            rec._ring = deque(rec._ring, maxlen=max(16, int(ring)))
    rec.configure(dir=flight_dir_for(cfg) or "", role=role,
                  job_name=getattr(cfg, "job_name", ""))
    rec.attach_tracing()
    rec.attach_logging()
    return rec


def install_crash_hooks(recorder: Optional[FlightRecorder] = None) -> None:
    """Wire the crash-shaped triggers onto the process recorder:
    sys.excepthook (unhandled exception), the fault injector's pre-crash
    hook (`<site>:crash` schedules), SIGUSR2 (explicit/offender trigger),
    and the opt-in atexit dump. Idempotent per process."""
    global _HOOKS_INSTALLED
    rec = recorder or get_recorder()
    if _HOOKS_INSTALLED:
        return
    _HOOKS_INSTALLED = True

    prev_hook = sys.excepthook

    def _excepthook(etype, value, tb):
        try:
            rec.record("event", "flight.crash", error=repr(value)[:256])
            rec.dump(f"crash:{etype.__name__}")
        except Exception:
            # the original exception must still surface:
            # edl-lint: disable=EDL303
            pass
        prev_hook(etype, value, tb)

    sys.excepthook = _excepthook

    # a `crash` fault action os._exit's (skipping atexit); the injector
    # runs these hooks first so the black box survives the simulated kill
    faults.add_crash_hook(lambda site: rec.dump(f"fault:{site}"))

    # SIGUSR2 must NOT dump inline: the handler runs on the main thread
    # between bytecodes, and dump() acquires the tracer/ring/registry
    # locks + does file I/O — if the signal lands while the main thread
    # (the train loop) is inside Tracer._emit or a registry mutation, an
    # inline dump deadlocks the very worker the offender snapshot was
    # meant to diagnose. The handler only sets an Event; a dedicated
    # daemon thread (which holds none of those locks) does the dump.
    trigger = threading.Event()

    def _drain_sigusr2():
        while True:
            trigger.wait()
            trigger.clear()
            rec.dump("sigusr2")

    def _on_sigusr2(signum, frame):
        trigger.set()

    try:
        signal.signal(signal.SIGUSR2, _on_sigusr2)
    except (ValueError, AttributeError, OSError):
        # not the main thread (in-process test workers) or no SIGUSR2 on
        # this platform: the other triggers still stand
        pass
    else:
        threading.Thread(
            target=_drain_sigusr2, name="edl-flight-sigusr2", daemon=True
        ).start()

    if os.environ.get(DUMP_ON_EXIT_ENV, "").strip().lower() in (
        "1", "true", "yes"
    ):
        atexit.register(lambda: rec.dump("exit"))


def reset_for_tests() -> None:
    """Drop the singleton + hook latch (tests only; triggers installed on
    sys/signal are NOT unwound — they chain harmlessly)."""
    global _RECORDER, _HOOKS_INSTALLED
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.detach_tracing()
            _RECORDER.detach_logging()
        _RECORDER = None
    _HOOKS_INSTALLED = False
