"""Cross-role incident correlation: merge flight bundles, traces, the
journal tail, and cluster-health snapshots into ONE timeline.

    python -m elasticdl_tpu.observability.incident <path> [path ...]
        [--json] [--strict] [--tail N]

A chaos failure (or a real one) leaves per-role evidence scattered: a
`flight-<role>-<pid>.json` bundle per process (observability/flight.py),
per-role `trace.jsonl` files, the master's replayed control-plane journal,
and `*health.json` rollup snapshots. This module reads all of it from one
directory (or explicit paths) and renders the incident as a single
timeline — the crash, the successor's recovery, each worker's reconnect,
straggler flags — ordered by wall clock, with the trace analyzer's
critical-path machinery (observability/analyzer.py) reused for any resize
timelines the records contain.

Tolerance contract (the analyzer's conventions):

- a bundle that fails to parse is a TORN bundle — tolerated and counted
  (the atomic tmp+replace writer means a torn bundle is itself evidence
  the writer died mid-incident), never a failure;
- a bundle that parses but violates the schema (no `records` list, no
  role) is a WRITER BUG: `--strict` exits 1;
- unparseable non-tail lines inside *.jsonl inputs are writer bugs too
  (`--strict` exits 1, via the analyzer's loader);
- a NAMED path that cannot be read at all is a USAGE error: exit 2.

Timeline entries are deduplicated across sources: a span that is both in
a worker's ring and in its trace.jsonl appears once.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from elasticdl_tpu.observability import analyzer
from elasticdl_tpu.observability.flight import BUNDLE_PREFIX

#: event names a postmortem reader always wants called out, whatever else
#: the ring carries
KEY_EVENT_NAMES = (
    "flight.crash", "flight.dump", "master.crash", "master.recovered",
    "worker.reconnect", "membership.reregister", "membership.death",
    "cluster.straggler", "cluster.straggler_cleared",
    "cluster.alert", "cluster.alert_cleared",
    "rpc.generation_handshake", "rpc.breaker_open", "rpc.breaker_reset",
    "reform.announce",
)

#: default journal-tail length carried into the report
TAIL_DEFAULT = 40


@dataclass
class LoadedBundles:
    bundles: List[dict] = field(default_factory=list)
    files: List[str] = field(default_factory=list)
    #: paths that failed to parse — the tolerated crash shape
    torn: List[str] = field(default_factory=list)
    #: (path, problem) pairs for parsed-but-malformed bundles (--strict)
    strict_violations: List[Tuple[str, str]] = field(default_factory=list)
    unreadable: List[str] = field(default_factory=list)


def _iter_bundle_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames.sort()
                for fn in sorted(filenames):
                    if fn.startswith(BUNDLE_PREFIX) and fn.endswith(".json"):
                        out.append(os.path.join(dirpath, fn))
        elif os.path.basename(p).startswith(BUNDLE_PREFIX):
            out.append(p)
    return out


def load_bundles(paths: Iterable[str]) -> LoadedBundles:
    loaded = LoadedBundles()
    for path in _iter_bundle_files(paths):
        loaded.files.append(path)
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except OSError:
            loaded.unreadable.append(path)
            continue
        except ValueError:
            loaded.torn.append(path)
            continue
        problem = None
        if not isinstance(data, dict):
            problem = "bundle is not a JSON object"
        elif not isinstance(data.get("records"), list):
            problem = "bundle has no records list"
        elif not data.get("role"):
            problem = "bundle carries no role"
        if problem is not None:
            loaded.strict_violations.append((path, problem))
            # still usable as far as it goes — a partial schema carries
            # partial evidence
            if isinstance(data, dict):
                loaded.bundles.append(data)
            continue
        data["_path"] = path
        loaded.bundles.append(data)
    return loaded


# ---------------------------------------------------------------------- #
# journal tail


def _journal_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames.sort()
                for fn in sorted(filenames):
                    if "journal.jsonl" in fn:
                        out.append(os.path.join(dirpath, fn))
        elif "journal.jsonl" in os.path.basename(p):
            out.append(p)
    return out


def _load_journal(paths: Iterable[str], tail: int) -> Optional[dict]:
    """Replay every journal file found (master/journal.py's replay is
    jsonl-only and protobuf-free) and keep the parsed tail — generation
    boundaries and the last transitions before/after the incident."""
    files = _journal_files(paths)
    if not files:
        return None
    from elasticdl_tpu.master.journal import replay_lines

    out: dict = {"files": files, "generations": [], "records": 0,
                 "dropped_lines": 0, "tail": []}
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            continue
        result = replay_lines(lines)
        out["records"] += result.records
        out["dropped_lines"] += result.dropped_lines
        out["generations"].append(result.prior_generation)
        if result.dispatcher is not None:
            # the wasted-work bill the journal carries (ISSUE 12,
            # observability/goodput.py): re-trained / discarded records,
            # per reason — the incident's data-plane cost
            d = result.dispatcher
            out["wasted_records"] = (
                out.get("wasted_records", 0) + d.wasted_records)
            out["wasted_events"] = (
                out.get("wasted_events", 0) + d.wasted_events)
            out["records_completed"] = (
                out.get("records_completed", 0) + d.records_completed)
            by = out.setdefault("wasted_by_reason", {})
            for reason, ent in d.wasted_by_reason.items():
                tot = by.setdefault(reason, {"events": 0, "records": 0})
                tot["events"] += ent.get("events", 0)
                tot["records"] += ent.get("records", 0)
        parsed = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                parsed.append(json.loads(line))
            except ValueError:
                continue
        out["tail"].extend(
            {"file": os.path.basename(path), **rec}
            for rec in parsed[-tail:]
        )
        out["world_version"] = max(
            out.get("world_version", 0), result.world_version
        )
        if result.layout is not None:
            # layout-controller decision history (ISSUE 20): rotation
            # snapshots carry the totals forward, so the latest file's
            # replayed state IS the cumulative history
            out["layout"] = {
                "actions_applied": result.layout.actions_applied,
                "by_kind": dict(result.layout.by_kind),
                "decision_records": result.layout.records,
                "last_action_ts": result.layout.last_action_ts,
            }
    out["generations"] = sorted(set(out["generations"]))
    return out


def _health_snapshots(paths: Iterable[str]) -> List[dict]:
    out: List[dict] = []
    for p in paths:
        candidates: List[str] = []
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames.sort()
                for fn in sorted(filenames):
                    if fn.endswith("health.json"):
                        candidates.append(os.path.join(dirpath, fn))
        elif p.endswith("health.json"):
            candidates.append(p)
        for path in candidates:
            try:
                with open(path, encoding="utf-8") as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(data, dict):
                data["_path"] = os.path.basename(path)
                out.append(data)
    return out


# ---------------------------------------------------------------------- #
# timeline assembly


def _entry_key(rec: dict) -> Tuple:
    """Dedup key across sources (ring + trace.jsonl carry the same
    records): identity is what/when/who, not which file it came from."""
    return (
        str(rec.get("kind", "")), str(rec.get("name", "")),
        round(float(rec.get("ts", 0.0)), 6), str(rec.get("role", "")),
        str(rec.get("span_id", "")),
    )


def _timeline_entry(rec: dict, source: str) -> Optional[dict]:
    ts = rec.get("ts")
    if not isinstance(ts, (int, float)):
        return None
    entry = {
        "ts": float(ts),
        "kind": str(rec.get("kind", "")),
        "name": str(rec.get("name", "")),
        "role": str(rec.get("role", "")),
        "source": source,
    }
    for k in ("dur_ms", "reason", "error", "level", "msg", "worker_id",
              "generation", "trace_id", "score", "world_version",
              "rule", "severity", "value", "threshold"):
        if k in rec and rec[k] is not None:
            entry[k] = rec[k]
    return entry


def correlate(paths: Iterable[str], tail: int = TAIL_DEFAULT) -> dict:
    """The incident report: bundles + traces + journal + health, merged."""
    paths = list(paths)
    bundles = load_bundles(paths)
    traces = analyzer.load_traces(paths)

    seen: Dict[Tuple, dict] = {}
    span_records: List[dict] = []

    def add(rec: dict, source: str) -> None:
        entry = _timeline_entry(rec, source)
        if entry is None:
            return
        key = _entry_key(rec)
        if key not in seen:
            seen[key] = entry
        if rec.get("kind") in ("span", "event") and rec.get("trace_id"):
            span_records.append(rec)

    for b in bundles.bundles:
        role = str(b.get("role", "?"))
        # the dump itself is a timeline fact: when the black box was cut
        add({
            "kind": "dump", "name": "flight.dump", "ts": b.get("ts"),
            "role": role, "reason": b.get("reason"),
            "world_version": b.get("world_version"),
        }, source="bundle")
        for rec in b.get("records") or []:
            if isinstance(rec, dict):
                rec = dict(rec)
                rec.setdefault("role", role)
                add(rec, source="bundle")
    for rec in traces.records:
        add(rec, source="trace")

    timeline = sorted(seen.values(), key=lambda e: (e["ts"], e["name"]))
    key_events = [
        e for e in timeline
        if e["name"] in KEY_EVENT_NAMES or e["kind"] in ("dump", "log")
    ]

    # resize/critical-path analysis over every span that carries a trace
    # id, pooled across bundles AND trace files (the analyzer dedups
    # nothing — feed it the deduped pool)
    pooled = list({_entry_key(r): r for r in span_records}.values())
    analysis = analyzer.analyze_records(pooled)

    journal = _load_journal(paths, tail)
    health = _health_snapshots(paths)

    # the incident's bill (ISSUE 12): wasted records from the replayed
    # journal + non-productive worker-seconds from the NEWEST fleet
    # goodput rollup any health snapshot carries ("this incident cost
    # 412 worker-seconds and 18k re-trained records")
    goodput_summary: dict = {}
    if journal and journal.get("wasted_records") is not None:
        goodput_summary["wasted_records"] = journal["wasted_records"]
        goodput_summary["wasted_events"] = journal.get("wasted_events", 0)
        goodput_summary["records_completed"] = journal.get(
            "records_completed", 0)
        goodput_summary["wasted_by_reason"] = journal.get(
            "wasted_by_reason", {})
    best_fleet = None
    best_ts = -1.0
    for snap in health:
        gp = snap.get("goodput") or {}
        fleet = gp.get("fleet") or {}
        if not fleet:
            continue
        # newest by the rollup's OWN timestamp, not by wall_s: reporter
        # churn (a killed worker's ledger leaving the sum) makes a
        # pre-incident snapshot's cumulative wall LARGER than the
        # post-incident one, and the summary must describe the latest
        # fleet state
        ts = gp.get("ts")
        ts = float(ts) if isinstance(ts, (int, float)) else 0.0
        if best_fleet is None or ts > best_ts:
            best_fleet = fleet
            best_ts = ts
    if best_fleet:
        cats = best_fleet.get("categories") or {}
        goodput_summary["fleet_goodput_fraction"] = best_fleet.get(
            "goodput_fraction")
        goodput_summary["fleet_wall_s"] = best_fleet.get("wall_s")
        goodput_summary["non_productive_worker_seconds"] = round(
            sum(v for c, v in cats.items() if c != "train_compute"), 3)

    slow_calls, diary_violations = _collect_slow_calls(bundles.bundles)

    report = {
        "paths": paths,
        "bundles": [
            {
                "role": b.get("role"), "pid": b.get("pid"),
                "reason": b.get("reason"), "ts": b.get("ts"),
                "records": len(b.get("records") or []),
                "world_version": b.get("world_version"),
                "dump_seq": b.get("dump_seq"),
                "file": os.path.basename(b.get("_path", "")),
            }
            for b in bundles.bundles
        ],
        "bundle_files": bundles.files,
        "torn_bundles": bundles.torn,
        "strict_violations": (
            [{"file": p, "problem": w} for p, w in bundles.strict_violations]
            + [
                {"file": p, "line": n, "problem": f"unparseable line: {t}"}
                for p, n, t in traces.strict_violations
            ]
            + diary_violations
        ),
        "unreadable_files": (
            list(bundles.unreadable) + list(traces.unreadable_files)
        ),
        "roles": sorted({
            str(b.get("role")) for b in bundles.bundles if b.get("role")
        } | {e["role"] for e in timeline if e["role"]}),
        "timeline": timeline,
        "key_events": key_events,
        "traces": analysis,
        "journal": journal,
        "health": health,
        "goodput": goodput_summary,
        "slow_calls": slow_calls,
    }
    return report


#: worst retained diaries rendered in the text report
SLOW_CALLS_SHOWN = 8
#: a retained diary's stages must sum to its wall within this (the
#: recorder completes the `other` residual at retain time, so a larger
#: gap is a writer bug, not timing noise)
ATTRIBUTION_TOL = 0.01


def _collect_slow_calls(bundles: List[dict]) -> Tuple[dict, List[dict]]:
    """Pool the `diaries` blocks across bundles (ISSUE 19): the worst
    retained request diaries plus the merged per-stage attribution —
    the section that names where the incident's p99 went. Returns
    (summary, strict_violations): a retained diary whose stages do NOT
    sum to its wall within 1% is a writer bug."""
    calls: List[dict] = []
    attr: Dict[str, float] = {}
    violations: List[dict] = []
    finished = retained = 0
    slow_wall = 0.0
    for b in bundles:
        block = b.get("diaries")
        if not isinstance(block, dict):
            continue
        role = str(b.get("role", "?"))
        fname = os.path.basename(b.get("_path", ""))
        finished += int(block.get("finished") or 0)
        retained += int(block.get("retained") or 0)
        wall = block.get("slow_wall_s")
        if isinstance(wall, (int, float)):
            slow_wall += float(wall)
        for s, v in (block.get("attribution") or {}).items():
            if isinstance(v, (int, float)):
                attr[s] = attr.get(s, 0.0) + float(v)
        for call in block.get("slow_calls") or []:
            if not isinstance(call, dict):
                continue
            calls.append({**call, "role": role})
            w = call.get("wall_s")
            stages = call.get("stages")
            if (isinstance(w, (int, float)) and w > 0
                    and isinstance(stages, dict)):
                total = sum(v for v in stages.values()
                            if isinstance(v, (int, float)))
                if abs(total - w) > max(ATTRIBUTION_TOL * w, 1e-5):
                    violations.append({
                        "file": fname,
                        "problem": (
                            f"diary {call.get('op', '?')} stages sum "
                            f"{total:.6f}s != wall {w:.6f}s (>1%)"),
                    })
    if not calls and not attr:
        return {}, violations
    calls.sort(key=lambda c: float(c.get("wall_s") or 0.0), reverse=True)
    named = {s: v for s, v in attr.items() if s != "other"}
    pool = named or attr
    dominant = max(sorted(pool), key=lambda s: pool[s]) if pool else None
    summary = {
        "finished": finished,
        "retained": retained,
        "slow_wall_s": round(slow_wall, 6),
        "attribution": {s: round(v, 6) for s, v in sorted(attr.items())},
        "dominant_stage": dominant,
        "dominant_share": (
            round(pool[dominant] / slow_wall, 4)
            if dominant is not None and slow_wall > 0 else None),
        "calls": calls,
    }
    return summary, violations


# ---------------------------------------------------------------------- #
# rendering


def _waterfall(call: dict, width: int = 24) -> List[str]:
    """One retained diary as an indented stage waterfall: each stage a
    bar proportional to its share of the call's wall, largest first."""
    wall = float(call.get("wall_s") or 0.0)
    stages = call.get("stages")
    if wall <= 0 or not isinstance(stages, dict):
        return []
    out: List[str] = []
    for s, v in sorted(stages.items(), key=lambda kv: -float(kv[1] or 0)):
        if not isinstance(v, (int, float)) or v <= 0:
            continue
        share = min(1.0, float(v) / wall)
        bar = "#" * max(1, int(round(share * width)))
        out.append(
            f"    {s:<12s} {float(v) * 1e3:9.2f}ms  "
            f"{bar:<{width}s} {share:.0%}"
        )
    return out


def render_text(report: dict, max_entries: int = 200) -> str:
    lines: List[str] = []
    bundles = report["bundles"]
    lines.append(
        f"incident: {len(bundles)} flight bundle(s) "
        f"[{', '.join(report['roles'])}]"
        + (f", {len(report['torn_bundles'])} torn" if report["torn_bundles"]
           else "")
    )
    for b in bundles:
        lines.append(
            f"  bundle {b['file'] or '?'}  role={b['role']} pid={b['pid']} "
            f"reason={b['reason']} records={b['records']} "
            f"world_v={b.get('world_version')}"
        )
    journal = report.get("journal")
    if journal:
        lines.append(
            f"journal: {journal['records']} record(s) across "
            f"generation(s) {journal['generations']}, "
            f"{journal['dropped_lines']} dropped line(s), "
            f"tail of {len(journal['tail'])} kept"
        )
        ly = journal.get("layout")
        if ly:
            by = "  ".join(
                f"{k}={v}" for k, v in sorted(ly["by_kind"].items()))
            lines.append(
                f"layout: {ly['actions_applied']} applied action(s) of "
                f"{ly['decision_records']} journaled decision(s)"
                + (f"  [{by}]" if by else "")
            )
    goodput = report.get("goodput") or {}
    if goodput:
        # the headline bill, in one sentence a capacity owner can read
        parts = []
        if goodput.get("non_productive_worker_seconds") is not None:
            parts.append(
                f"{goodput['non_productive_worker_seconds']:g} "
                "non-productive worker-seconds"
            )
        if goodput.get("wasted_records") is not None:
            parts.append(
                f"{goodput['wasted_records']} re-trained/discarded "
                "record(s)"
            )
        if parts:
            lines.append("goodput: this incident cost " + " and ".join(parts))
        if goodput.get("fleet_goodput_fraction") is not None:
            lines.append(
                f"  fleet goodput fraction "
                f"{goodput['fleet_goodput_fraction']:.3f} over "
                f"{goodput.get('fleet_wall_s', 0):g} worker-seconds"
            )
        for reason, ent in sorted(
            (goodput.get("wasted_by_reason") or {}).items()
        ):
            lines.append(
                f"  wasted[{reason}]: {ent.get('records', 0)} record(s) "
                f"across {ent.get('events', 0)} event(s)"
            )
    slow = report.get("slow_calls") or {}
    if slow:
        dom = slow.get("dominant_stage")
        share = slow.get("dominant_share")
        head = (
            f"slow_calls: {slow.get('retained', 0)} retained of "
            f"{slow.get('finished', 0)} finished, "
            f"{slow.get('slow_wall_s', 0):g}s slow wall"
        )
        if dom:
            head += f" — dominant stage {dom}"
            if share is not None:
                head += f" ({share:.0%} of the slow wall)"
        lines.append(head)
        for call in (slow.get("calls") or [])[:SLOW_CALLS_SHOWN]:
            lines.append(
                f"  {call.get('op', '?'):<10s} "
                f"{float(call.get('wall_s') or 0.0) * 1e3:9.2f}ms "
                f"{call.get('status', '?'):<8s} "
                f"[{call.get('role', '?')}]"
                + (f"  {call['detail']}" if call.get("detail") else "")
            )
            lines.extend(_waterfall(call))
    for snap in report.get("health") or ():
        # snapshot_age_s (ISSUE 11): how stale the rollup was when it
        # was served — the difference between "the fleet was fine" and
        # "the master stopped looking"
        age = snap.get("snapshot_age_s")
        cluster = snap.get("cluster") or snap
        if age is None:
            age = cluster.get("snapshot_age_s")
        lines.append(
            f"health {snap.get('_path', '?')}: "
            f"{cluster.get('workers_reporting', 0)} reporting, "
            f"{cluster.get('straggler_count', 0)} straggler(s), "
            f"skew {cluster.get('skew', 1.0)}"
            + (f", rollup age {age}s" if age is not None else "")
        )

    timeline = report["timeline"]
    shown = timeline
    note = ""
    if len(timeline) > max_entries:
        # keep every key event + the most recent tail, in order
        keep = {id(e) for e in report["key_events"]}
        keep |= {id(e) for e in timeline[-max_entries:]}
        shown = [e for e in timeline if id(e) in keep]
        note = f" (showing {len(shown)} of {len(timeline)})"
    lines.append(f"timeline{note}:")
    t0 = timeline[0]["ts"] if timeline else 0.0
    for e in shown:
        extra = ""
        for k in ("reason", "error", "msg", "worker_id", "generation",
                  "rule", "severity", "value"):
            if k in e:
                extra += f" {k}={e[k]}"
        dur = f" {e['dur_ms']:.1f}ms" if "dur_ms" in e else ""
        lines.append(
            f"  +{e['ts'] - t0:9.3f}s  [{e['role'] or '?':<12s}] "
            f"{e['kind']:<5s} {e['name']}{dur}{extra}"
        )
    resize = report["traces"].get("resize_traces", 0)
    if resize:
        lines.append(f"{resize} resize timeline(s) — critical paths:")
        for t in report["traces"]["traces"]:
            tl = t.get("timeline")
            if not t["is_resize"] or not tl:
                continue
            phases = "  ".join(
                f"{k}={v:.3f}s" for k, v in tl["phases"].items()
            )
            lines.append(
                f"  trace {t['trace_id']}: wall {tl['wall_s']:.3f}s  {phases}"
            )
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.observability.incident",
        description="correlate flight bundles, traces, the journal tail "
                    "and health snapshots into one incident timeline",
    )
    parser.add_argument(
        "paths", nargs="+",
        help="directories (walked for flight-*.json / *.jsonl / "
             "*health.json) and/or explicit files",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the full JSON report instead of text",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="exit 1 on writer bugs (malformed-but-parseable bundles, "
             "unparseable non-tail trace lines); torn bundles — the "
             "documented crash shape — stay tolerated",
    )
    parser.add_argument(
        "--tail", type=int, default=TAIL_DEFAULT,
        help=f"journal-tail records to keep (default {TAIL_DEFAULT})",
    )
    args = parser.parse_args(argv)

    report = correlate(args.paths, tail=args.tail)
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True, default=repr))
    else:
        print(render_text(report), end="")

    have_inputs = (
        report["bundle_files"] or report["torn_bundles"]
        or report["timeline"] or report.get("journal")
    )
    if not have_inputs:
        print("no incident inputs found", file=sys.stderr)
        return 2
    if report["unreadable_files"]:
        for path in report["unreadable_files"]:
            print(f"unreadable input file: {path}", file=sys.stderr)
        return 2
    if args.strict and report["strict_violations"]:
        for v in report["strict_violations"]:
            print(
                f"strict: {v['file']}: {v['problem']}", file=sys.stderr,
            )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
