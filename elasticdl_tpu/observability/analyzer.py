"""Offline trace analysis: merge trace.jsonl files into per-resize
timelines and compute the critical path.

A resize writes spans from several processes — the master's
reform.announce/quiesce/teardown/spawn and every worker's
boot/rescale/compile/handoff work — stitched by one trace id
(observability/tracing.py). Reading that by hand means grepping N files
and mentally subtracting timestamps; this module does the arithmetic:

- **merge**: load any number of trace.jsonl files (or directories, walked
  for ``*.jsonl``), tolerating torn tails (a writer killed mid-record) and
  interleaved garbage lines, and group records by trace id;
- **critical path**: per trace, rebuild the span tree (parent ids only
  link within a process, so a cross-role trace has several roots — they
  become children of a synthetic ``timeline`` root spanning the whole
  incident) and walk the classic latest-ending-child chain: starting from
  a span's end, repeatedly attribute the interval to the latest-ending
  child that fits, recursing; uncovered gaps are the span's own time.
  Every instant of the timeline is attributed to exactly ONE segment, so
  the segment durations sum to the wall clock by construction — that is
  the property the bench leans on ("phase sum consistent with measured
  recovery wall-clock");
- **attribution**: segments roll up per phase (settle / handoff /
  compile / other, by span-name classification) and per role (master,
  worker-N, ...), answering "where did the resize actually spend its
  time" without reading a single raw line.

CLI: ``python -m elasticdl_tpu.observability.analyze <paths> [--json]
[--strict] [--trace-id ID]`` (analyze.py). ``--strict`` fails (exit 1) on
any unparseable line that is NOT the final line of its file — a torn tail
is the documented crash shape and stays tolerated; garbage anywhere else
means a writer bug and CI should say so. `bench.py rescale` runs
`analyze_records` on its own span buffer so the critical path joins the
perf trajectory. Stdlib-only, jax-free, like the rest of the package.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

#: span names that mark a trace as a resize/recovery timeline
RESIZE_ROOT_NAMES = ("rescale", "reform", "timeline")

#: tolerance (seconds) for clock skew / float rounding when chaining
#: child spans — cross-process timestamps are wall clocks
EPS_S = 1e-4

#: span-name keyword -> phase classification, first match wins. "settle"
#: covers membership/world mechanics, "handoff" state movement,
#: "compile" executable builds; everything else is "other".
PHASE_KEYWORDS = (
    ("compile", ("compile",)),
    ("handoff", ("handoff", "drain", "stage_to_host", "ckpt")),
    ("settle", ("settle", "mesh", "world_form", "quiesce", "teardown",
                "spawn", "register", "build", "reform")),
)


def classify_phase(name: str) -> str:
    for phase, keys in PHASE_KEYWORDS:
        if any(k in name for k in keys):
            return phase
    return "other"


# ---------------------------------------------------------------------- #
# loading


@dataclass
class LoadedTraces:
    records: List[dict]
    files: List[str]
    #: (path, line_number, text-prefix) of every unparseable line
    bad_lines: List[Tuple[str, int, str]]
    #: bad lines that are NOT the final line of their file (--strict fails
    #: on these; a torn tail is the tolerated crash shape)
    strict_violations: List[Tuple[str, int, str]]
    #: named files that could not be opened at all — a USAGE problem (the
    #: writer never ran, the path is wrong), distinct from writer bugs:
    #: the CLI exits 2 for these, never 1
    unreadable_files: List[str]


def _iter_trace_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames.sort()
                for fn in sorted(filenames):
                    # metrics_history files (observability/timeseries.py;
                    # chaos writes them PREFIXED, chaos-smoke-seedN.
                    # metrics_history.jsonl) are jsonl but not traces:
                    # their lines parse fine and would pollute the record
                    # pool with value rows — substring match, like the
                    # journal walk
                    if fn.endswith(".jsonl") and "metrics_history" not in fn:
                        out.append(os.path.join(dirpath, fn))
        else:
            out.append(p)
    return out


def load_traces(paths: Iterable[str]) -> LoadedTraces:
    """Read every trace file under `paths`. Unparseable lines are counted,
    never fatal: the analyzer's whole job includes reading the traces of
    processes that died mid-write."""
    records: List[dict] = []
    bad: List[Tuple[str, int, str]] = []
    strict: List[Tuple[str, int, str]] = []
    unreadable: List[str] = []
    files = _iter_trace_files(paths)
    for path in files:
        file_bad: List[Tuple[int, str]] = []
        last_nonempty = 0
        try:
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, start=1):
                    line = line.strip()
                    if not line:
                        continue
                    last_nonempty = lineno
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        file_bad.append((lineno, line[:80]))
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
                    else:
                        file_bad.append((lineno, line[:80]))
        except OSError:
            unreadable.append(path)
            continue
        for lineno, text in file_bad:
            bad.append((path, lineno, text))
            if lineno != last_nonempty:
                strict.append((path, lineno, text))
    return LoadedTraces(
        records=records, files=files, bad_lines=bad,
        strict_violations=strict, unreadable_files=unreadable,
    )


# ---------------------------------------------------------------------- #
# span tree + critical path


@dataclass
class _Node:
    name: str
    role: str
    span_id: str
    parent_id: Optional[str]
    start: float
    dur: float
    children: List["_Node"] = field(default_factory=list)

    @property
    def end(self) -> float:
        return self.start + self.dur


@dataclass
class Segment:
    """One critical-path slice: [start, start+dur) attributed to `name`.
    `self_time` marks a parent span's own (un-childed) interval."""

    name: str
    role: str
    start: float
    dur: float
    self_time: bool = False


def _build_nodes(spans: List[dict]) -> Tuple[List[_Node], List[_Node]]:
    """(all nodes, roots). Spans missing timing fields are dropped —
    they cannot be placed on a timeline."""
    nodes: Dict[str, _Node] = {}
    ordered: List[_Node] = []
    for r in spans:
        ts, dur = r.get("ts"), r.get("dur_ms")
        sid = r.get("span_id")
        if not isinstance(ts, (int, float)) or not isinstance(dur, (int, float)):
            continue
        node = _Node(
            name=str(r.get("name", "?")),
            role=str(r.get("role", "")),
            span_id=str(sid) if sid else f"anon-{len(ordered)}",
            parent_id=r.get("parent_id") or None,
            start=float(ts),
            dur=max(0.0, float(dur) / 1e3),
        )
        nodes[node.span_id] = node
        ordered.append(node)
    roots: List[_Node] = []
    for n in ordered:
        parent = nodes.get(n.parent_id) if n.parent_id else None
        if parent is not None and parent is not n:
            parent.children.append(n)
        else:
            roots.append(n)
    for n in ordered:
        n.children.sort(key=lambda k: (k.start, k.end))
    return ordered, roots


def _walk_critical(node: _Node, out: List[Segment]) -> None:
    """Attribute [node.start, node.end) to segments: the latest-ending
    child chain is the critical path; intervals no child covers are the
    node's own time. Children overlapping the already-attributed tail
    (parallel work that finished earlier) are off-path by definition —
    shortening them would not move the end time."""
    cursor = node.end
    for child in sorted(node.children, key=lambda k: k.end, reverse=True):
        if child.end > cursor + EPS_S or child.start < node.start - EPS_S:
            continue    # overlaps the chosen chain, or outside the parent
        if cursor - child.end > EPS_S:
            out.append(Segment(
                name=node.name, role=node.role,
                start=child.end, dur=cursor - child.end, self_time=True,
            ))
        _walk_critical(child, out)
        cursor = child.start
    if cursor - node.start > EPS_S or not node.children:
        out.append(Segment(
            name=node.name, role=node.role,
            start=node.start, dur=max(0.0, cursor - node.start),
            self_time=bool(node.children),
        ))


def critical_path(root: _Node) -> List[Segment]:
    segs: List[Segment] = []
    _walk_critical(root, segs)
    segs.sort(key=lambda s: s.start)
    return segs


def _root_summary(root: _Node) -> dict:
    segs = critical_path(root)
    phases: Dict[str, float] = {}
    by_role: Dict[str, float] = {}
    for s in segs:
        phases[classify_phase(s.name)] = (
            phases.get(classify_phase(s.name), 0.0) + s.dur
        )
        by_role[s.role] = by_role.get(s.role, 0.0) + s.dur
    return {
        "name": root.name,
        "role": root.role,
        "start_ts": round(root.start, 6),
        "wall_s": round(root.dur, 6),
        "critical_path": [
            {
                "name": s.name + (" (self)" if s.self_time else ""),
                "role": s.role,
                "offset_s": round(s.start - root.start, 6),
                "dur_s": round(s.dur, 6),
            }
            for s in segs
        ],
        "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
        "by_role": {k: round(v, 6) for k, v in sorted(by_role.items())},
    }


# ---------------------------------------------------------------------- #
# per-trace analysis


def _analyze_trace(trace_id: str, records: List[dict]) -> dict:
    spans = [r for r in records if r.get("kind") == "span"]
    events = [r for r in records if r.get("kind") == "event"]
    _, roots = _build_nodes(spans)
    roots.sort(key=lambda n: (n.start, n.end))
    summary: dict = {
        "trace_id": trace_id,
        "spans": len(spans),
        "events": len(events),
        "roles": sorted({
            str(r.get("role", "")) for r in records if r.get("role")
        }),
        "is_resize": any(
            n.name in RESIZE_ROOT_NAMES for n in roots
        ) or any(
            str(e.get("name", "")).startswith("reform.") for e in events
        ),
        "event_names": sorted({str(e.get("name", "")) for e in events}),
        "straggler_events": [
            {k: e.get(k) for k in
             ("worker_id", "score", "step_time_p50_s", "ts")}
            for e in events if e.get("name") == "cluster.straggler"
        ],
        "roots": [],
    }
    if not roots:
        summary["timeline"] = None
        return summary
    summary["roots"] = [_root_summary(n) for n in roots]
    if len(roots) == 1:
        # single-root trace: the timeline IS that root's summary (already
        # computed — the recursive walk is the analysis cost, and CI runs
        # this over every artifact)
        summary["timeline"] = summary["roots"][0]
    else:
        # cross-role timelines: parent ids never link across processes,
        # so a synthetic root spans the whole incident and chains the
        # per-process roots (master reform -> worker rescale) for one
        # end-to-end critical path
        start = min(n.start for n in roots)
        end = max(n.end for n in roots)
        summary["timeline"] = _root_summary(_Node(
            name="timeline", role="", span_id="timeline", parent_id=None,
            start=start, dur=end - start, children=list(roots),
        ))
    return summary


def analyze_records(records: List[dict],
                    trace_id: Optional[str] = None) -> dict:
    """Group records by trace id and analyze each; `trace_id` restricts
    to one. Traces are ordered by first-record timestamp — deterministic
    for any fixed input."""
    by_trace: Dict[str, List[dict]] = {}
    for r in records:
        tid = r.get("trace_id")
        if not tid:
            continue
        if trace_id is not None and tid != trace_id:
            continue
        by_trace.setdefault(str(tid), []).append(r)

    def first_ts(recs: List[dict]) -> float:
        tss = [r["ts"] for r in recs if isinstance(r.get("ts"), (int, float))]
        return min(tss) if tss else 0.0

    traces = [
        _analyze_trace(tid, recs)
        for tid, recs in sorted(
            by_trace.items(), key=lambda kv: (first_ts(kv[1]), kv[0])
        )
    ]
    return {
        "records": len(records),
        "traces": traces,
        "resize_traces": sum(1 for t in traces if t["is_resize"]),
    }


def analyze_paths(paths: Iterable[str],
                  trace_id: Optional[str] = None) -> dict:
    loaded = load_traces(paths)
    report = analyze_records(loaded.records, trace_id=trace_id)
    report["files"] = loaded.files
    report["unparseable_lines"] = [
        {"file": p, "line": n, "text": t} for p, n, t in loaded.bad_lines
    ]
    report["strict_violations"] = [
        {"file": p, "line": n, "text": t}
        for p, n, t in loaded.strict_violations
    ]
    report["unreadable_files"] = list(loaded.unreadable_files)
    return report


def resize_timeline(report: dict, trace_id: str) -> Optional[dict]:
    """Convenience: one trace's summary out of a report (bench uses it)."""
    for t in report.get("traces", ()):
        if t["trace_id"] == trace_id:
            return t
    return None


# ---------------------------------------------------------------------- #
# text rendering


def render_text(report: dict, resize_only: bool = True) -> str:
    lines: List[str] = []
    traces = report.get("traces", [])
    shown = [t for t in traces if t["is_resize"]] if resize_only else traces
    if resize_only and not shown:
        shown = traces
    lines.append(
        f"{report.get('records', 0)} records, {len(traces)} trace(s), "
        f"{report.get('resize_traces', 0)} resize timeline(s)"
        + (f", {len(report['unparseable_lines'])} unparseable line(s)"
           if report.get("unparseable_lines") else "")
    )
    for t in shown:
        tl = t.get("timeline")
        lines.append("")
        lines.append(
            f"trace {t['trace_id']}  [{', '.join(t['roles'])}]  "
            f"{t['spans']} span(s), {t['events']} event(s)"
            + ("  RESIZE" if t["is_resize"] else "")
        )
        if tl is None:
            lines.append("  (no timed spans)")
            continue
        lines.append(f"  wall {tl['wall_s']:.3f}s  critical path:")
        for seg in tl["critical_path"]:
            lines.append(
                f"    +{seg['offset_s']:8.3f}s  {seg['dur_s']:8.3f}s  "
                f"{seg['name']:<28s} [{seg['role']}]"
            )
        phase_sum = sum(tl["phases"].values())
        phase_txt = "  ".join(
            f"{k}={v:.3f}s" for k, v in tl["phases"].items()
        )
        lines.append(f"  phases: {phase_txt}  (sum {phase_sum:.3f}s)")
        role_txt = "  ".join(
            f"{k or '<gap>'}={v:.3f}s" for k, v in tl["by_role"].items()
        )
        lines.append(f"  by role: {role_txt}")
        if t["straggler_events"]:
            lines.append(
                f"  stragglers flagged: "
                f"{[e['worker_id'] for e in t['straggler_events']]}"
            )
    return "\n".join(lines) + "\n"
