"""In-process metrics time series: a bounded ring of periodic registry
snapshots with counter-aware window queries.

Everything the registry (PR 4) exports is point-in-time: a scrape sees
the fleet NOW, and the moment before is gone. The autoscaler (ROADMAP 3)
and every alert condition worth declaring ("backlog per worker high for
30s", "data_wait-dominant for two windows") need *histories*. This
module gives each process one:

- **`TimeSeriesStore`**: a bounded deque of `(ts, {series: value})`
  samples taken from the registry's flat snapshot (the same names
  `/metrics` serves), plus any caller-provided *extra* series — the
  master feeds fleet aggregates computed from the heartbeat stats
  payloads it already receives (`fleet_series`, below). Sampling is
  rate-limited (`maybe_sample`, default every 5 s) so wiring it into a
  poll/heartbeat/step loop costs a clock read almost always.
- **counter awareness**: each series remembers its metric kind at sample
  time. `rate()` computes a per-second increase that survives counter
  RESETS (a process restart zeroes its counters; the increase since the
  reset is the post-reset value, Prometheus-style) — `delta()` is the
  same sum without the time division. `avg()`/`quantile()` read gauge
  series over a window.
- **rolling persistence**: with a history path configured, every sample
  appends one JSON line to `metrics_history.jsonl`; past
  `history_max_lines` the file is compacted to its newest half via the
  atomic tmp+`os.replace` discipline (EDL305) — the on-disk history is
  bounded like the in-memory ring. All file I/O happens OUTSIDE the
  store lock, and a write failure disables persistence loudly rather
  than costing the sampler again and again.
- **`GET /timeseries`** (observability/http.py) serves `to_payload()`:
  recent samples + per-series window stats, so a scraper (or the
  incident CLI's operator) can pull the history without ssh.

Stdlib-only and jax-free like the rest of the package; the store lock is
a LEAF lock (nothing inside it acquires anything else).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import default_logger
# the ONE median implementation (health.py owns it — its docstring warns
# that diverging copies let the scorer's threshold math and the exported
# fleet statistics disagree); health.py does not import this module, so
# the import is cycle-free
from elasticdl_tpu.observability.health import median as _median
from elasticdl_tpu.observability.registry import (
    MetricsRegistry,
    default_registry,
    quantile_sorted,
)

logger = default_logger(__name__)

#: default sampling cadence (seconds) — coarse enough that a per-step
#: maybe_sample() is a clock read, fine enough for minute-scale alerting
INTERVAL_DEFAULT_S = 5.0

#: default ring capacity (samples): 720 x 5 s = one hour of history
CAPACITY_DEFAULT = 720

#: default on-disk bound for metrics_history.jsonl before compaction
HISTORY_MAX_LINES = 4096

#: the canonical history filename (docs/observability.md "Time series")
HISTORY_BASENAME = "metrics_history.jsonl"


def _reset_aware_delta(pts: List[Tuple[float, float]]) -> float:
    """Counter increase over (ts, value) points, surviving RESETS: a
    sample lower than its predecessor means the counter restarted from
    zero, and the post-reset value IS the increase since (Prometheus
    rate() semantics). The one implementation delta() and the
    /timeseries payload share."""
    total = 0.0
    prev = pts[0][1]
    for _, v in pts[1:]:
        total += (v - prev) if v >= prev else v
        prev = v
    return total


def _snapshot_with_kinds(registry: MetricsRegistry):
    """(values, kinds) in ONE pass over the registry — kind is "counter"
    or "gauge" for rate awareness. Summary series decompose: `_count`/
    `_sum` behave like counters, quantile series like gauges. One pass
    because this runs on the sampling cadence and each metric snapshot
    has real cost (histogram reservoirs sort)."""
    values: Dict[str, float] = {}
    kinds: Dict[str, str] = {}
    for metric in registry.metrics():
        try:
            snap = metric.snapshot()
        except Exception:
            # one broken metric must not take sampling down:
            # edl-lint: disable=EDL303
            continue
        values.update(snap)
        if metric.kind == "counter":
            for name in snap:
                kinds[name] = "counter"
        elif metric.kind == "summary":
            for name in snap:
                base = name.split("{", 1)[0]
                kinds[name] = (
                    "counter"
                    if base.endswith("_count") or base.endswith("_sum")
                    else "gauge"
                )
        else:
            for name in snap:
                kinds[name] = "gauge"
    return values, kinds


class TimeSeriesStore:
    """Bounded ring of registry snapshots + window queries over it."""

    def __init__(self, capacity: int = CAPACITY_DEFAULT,
                 interval_s: float = INTERVAL_DEFAULT_S,
                 registry: Optional[MetricsRegistry] = None,
                 history_path: Optional[str] = None,
                 history_max_lines: int = HISTORY_MAX_LINES):
        self._registry = registry or default_registry()
        self.interval_s = max(0.0, float(interval_s))
        self._lock = threading.Lock()
        self._samples: "deque[Tuple[float, Dict[str, float]]]" = deque(
            maxlen=max(8, int(capacity)))                # guarded_by: _lock
        self._kinds: Dict[str, str] = {}                 # guarded_by: _lock
        self._last_sample_ts = 0.0                       # guarded_by: _lock
        self._sample_count = 0                           # guarded_by: _lock
        self.history_path = history_path or None
        self._history_max_lines = max(16, int(history_max_lines))
        self._history_lines = 0         # appended since the last compaction
        self._history_failed = False

    # ------------------------------------------------------------------ #
    # configuration

    def configure(self, history_path: Optional[str] = None,
                  interval_s: Optional[float] = None,
                  capacity: Optional[int] = None) -> "TimeSeriesStore":
        """(Re)point the store; None keeps the current value. "" for
        history_path means memory-only."""
        if history_path is not None:
            self.history_path = history_path or None
            self._history_failed = False
            self._history_lines = 0
        if interval_s is not None:
            self.interval_s = max(0.0, float(interval_s))
        if capacity is not None:
            with self._lock:
                self._samples = deque(
                    self._samples, maxlen=max(8, int(capacity)))
        return self

    # ------------------------------------------------------------------ #
    # sampling

    def maybe_sample(self, now: Optional[float] = None,
                     extra_fn: Optional[Callable[[], Dict[str, float]]]
                     = None) -> bool:
        """Take a sample iff the interval elapsed (the cheap call loops
        wire in — a lock + clock compare when not due). `extra_fn` is
        only invoked when a sample is actually taken (fleet aggregation
        has a real cost; don't pay it 5x/second for nothing)."""
        now = time.time() if now is None else now
        with self._lock:
            if now - self._last_sample_ts < self.interval_s:
                return False
        extra = None
        if extra_fn is not None:
            try:
                extra = extra_fn()
            except Exception:
                # the sampler is called from control loops whose contract
                # is "never raises" — a broken aggregator costs its
                # series, not the master: edl-lint: disable=EDL303
                logger.exception("time-series extra_fn failed; sampling "
                                 "registry only")
        self.sample(now=now, extra=extra)
        return True

    def sample(self, now: Optional[float] = None,
               extra: Optional[Dict[str, float]] = None) -> Dict[str, float]:
        """Unconditionally snapshot the registry (+ extra series) into the
        ring; returns the sampled values. Never raises."""
        now = time.time() if now is None else now
        try:
            values, kinds = _snapshot_with_kinds(self._registry)
        except Exception:
            # a broken metric callback must not take sampling down:
            # edl-lint: disable=EDL303
            values, kinds = {}, {}
        if extra:
            for k, v in extra.items():
                try:
                    values[k] = float(v)
                except (TypeError, ValueError):
                    continue
                # extra series follow the metric naming convention:
                # *_total reads as a counter, everything else as a gauge
                kinds.setdefault(
                    k, "counter" if k.endswith("_total") else "gauge")
        with self._lock:
            self._samples.append((now, values))
            self._kinds.update(kinds)
            self._last_sample_ts = now
            self._sample_count += 1
        self._persist(now, values)
        return values

    @property
    def sample_count(self) -> int:
        with self._lock:
            return self._sample_count

    # ------------------------------------------------------------------ #
    # window queries

    def window(self, series: str, window_s: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """(ts, value) pairs for `series` within the last `window_s`
        seconds (ascending ts; samples where the series is absent are
        skipped — a series can appear mid-history)."""
        now = time.time() if now is None else now
        lo = now - max(0.0, float(window_s))
        with self._lock:
            return [
                (ts, vals[series])
                for ts, vals in self._samples
                if lo <= ts <= now and series in vals
            ]

    def latest(self, series: str,
               now: Optional[float] = None,
               max_age_s: Optional[float] = None) -> Optional[float]:
        """Most recent value of `series` (None = never sampled, or older
        than `max_age_s` when given)."""
        with self._lock:
            for ts, vals in reversed(self._samples):
                if series in vals:
                    if max_age_s is not None:
                        now_ = time.time() if now is None else now
                        if now_ - ts > max_age_s:
                            return None
                    return vals[series]
        return None

    def kind(self, series: str) -> str:
        with self._lock:
            return self._kinds.get(series, "gauge")

    def delta(self, series: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Counter increase over the window, RESET-aware: a sample lower
        than its predecessor means the counter restarted from zero, and
        the post-reset value IS the increase since (Prometheus rate()
        semantics). None = fewer than 2 samples in the window."""
        pts = self.window(series, window_s, now=now)
        if len(pts) < 2:
            return None
        return _reset_aware_delta(pts)

    def rate(self, series: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second counter rate over the window (reset-aware); None =
        not enough samples or a zero-width window."""
        pts = self.window(series, window_s, now=now)
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        d = self.delta(series, window_s, now=now)
        return None if d is None else d / span

    def avg(self, series: str, window_s: float,
            now: Optional[float] = None) -> Optional[float]:
        pts = self.window(series, window_s, now=now)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def quantile(self, series: str, q: float, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        pts = self.window(series, window_s, now=now)
        if not pts:
            return None
        return quantile_sorted(sorted(v for _, v in pts), q)

    def series_names(self) -> List[str]:
        with self._lock:
            names = set()
            for _, vals in self._samples:
                names.update(vals)
            return sorted(names)

    # ------------------------------------------------------------------ #
    # /timeseries payload

    def to_payload(self, window_s: float = 300.0,
                   series: Optional[Iterable[str]] = None,
                   now: Optional[float] = None) -> Dict:
        """What GET /timeseries serves: recent samples (sparse — only
        requested/changed series) + per-series window stats. Cheap: one
        ring copy under the lock, arithmetic outside."""
        now = time.time() if now is None else now
        lo = now - max(0.0, float(window_s))
        with self._lock:
            samples = [(ts, dict(vals)) for ts, vals in self._samples
                       if ts >= lo]
            kinds = dict(self._kinds)
            count = self._sample_count
        wanted = set(series) if series else None
        names: set = set()
        for _, vals in samples:
            names.update(vals)
        if wanted is not None:
            names &= wanted
        stats: Dict[str, Dict] = {}
        for name in sorted(names):
            pts = [(ts, vals[name]) for ts, vals in samples
                   if name in vals]
            if not pts:
                continue
            vs = sorted(v for _, v in pts)
            entry: Dict = {
                "kind": kinds.get(name, "gauge"),
                "points": len(pts),
                "latest": pts[-1][1],
                "avg": sum(vs) / len(vs),
                "p99": quantile_sorted(vs, 0.99),
            }
            if entry["kind"] == "counter" and len(pts) >= 2:
                span = pts[-1][0] - pts[0][0]
                total = _reset_aware_delta(pts)
                entry["delta"] = total
                if span > 0:
                    entry["rate_per_s"] = total / span
            stats[name] = entry
        return {
            "ts": now,
            "window_s": float(window_s),
            "interval_s": self.interval_s,
            "sample_count": count,
            "samples_in_window": len(samples),
            "series": stats,
            "samples": [
                {"ts": ts,
                 "values": ({k: v for k, v in vals.items() if k in wanted}
                            if wanted is not None else vals)}
                for ts, vals in samples
            ],
        }

    # ------------------------------------------------------------------ #
    # rolling history file

    def _persist(self, ts: float, values: Dict[str, float]) -> None:
        """Append one history line; compact past the line bound. File I/O
        happens with NO store lock held and never raises — persistence is
        an observability convenience, not a correctness surface."""
        path = self.history_path
        if not path or self._history_failed:
            return
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            line = json.dumps(
                {"ts": round(ts, 3), "values": values}, sort_keys=True)
            with open(path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
            self._history_lines += 1
            if self._history_lines >= self._history_max_lines:
                self._compact_history(path)
        except OSError:
            # disable loudly ONCE: a full/readonly disk must not cost the
            # sampler an exception per interval forever
            self._history_failed = True
            logger.exception(
                "metrics history persistence to %s failed; disabled", path)

    def _compact_history(self, path: str) -> None:
        """Rewrite the history to its newest half, atomically (tmp +
        os.replace — EDL305): the on-disk file stays bounded at ~1.5x
        history_max_lines worst case."""
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
        keep = lines[-(self._history_max_lines // 2):]
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.writelines(keep)
        os.replace(tmp, path)
        self._history_lines = 0

    def close(self) -> None:
        """Nothing buffered to flush (appends land per sample); kept for
        symmetric lifecycle wiring."""


# ---------------------------------------------------------------------- #
# fleet aggregation (master-side): heartbeat stats records -> series

#: profiler phase keys summed for the data_wait fraction
_PHASE_KEYS = ("phase_data_wait_ms", "phase_h2d_ms", "phase_compute_ms",
               "phase_handoff_ms")


def fleet_series(health_records: List[Dict],
                 straggler_count: int = 0,
                 todo_tasks: Optional[int] = None,
                 alive_workers: Optional[int] = None,
                 stale_after_s: float = 30.0,
                 now: Optional[float] = None) -> Dict[str, float]:
    """Fleet-level series computed from the per-worker heartbeat stats
    records `Membership` already accumulates — the master's `extra_fn`
    for `maybe_sample()`, and the sensor set the default alert rules
    (observability/alerts.py) read. Every series is a gauge named
    `edl_fleet_*`:

    - `edl_fleet_workers_reporting`       workers with fresh telemetry
    - `edl_fleet_step_p50_ms_median`      fleet median of step-time p50s
    - `edl_fleet_straggler_count`         pass-through from ClusterHealth
    - `edl_fleet_backlog_per_worker`      dispatcher todo / alive workers
    - `edl_fleet_data_wait_frac`          median fraction of step time
                                          spent blocked on input
    - `edl_fleet_emb_pull_p99_ms`         worst client OWNER-RPC pull p99
    - `edl_fleet_emb_read_p99_ms`         worst effective read p99
                                          (cache/pipeline included)
    - `edl_fleet_emb_hot_id_share`        worst hot-id traffic share
    - `edl_fleet_emb_shard_imbalance`     worst shard load imbalance
    - `edl_fleet_emb_cache_hit_rate`      WORST (lowest) recent hot-row
                                          cache hit rate — the hot-set
                                          migration / collapse sensor

    Embedding series appear only when at least one worker's payload
    carried them (the tier is optional). Absence of a series is visible
    to rules as "no data" — they carry active alerts forward rather than
    clearing on blindness.
    """
    now = time.time() if now is None else now

    def num(rec: Dict, key: str) -> Optional[float]:
        # heartbeat payloads admit STRING values too (decode_stats keeps
        # v[:64] from a mixed-version worker) — a non-numeric value must
        # read as absent, never raise out of the master's sampler
        v = rec.get(key)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return float(v)

    fresh = [
        r for r in health_records
        if now - (num(r, "updated_at") or 0.0) <= stale_after_s
    ]
    out: Dict[str, float] = {
        "edl_fleet_workers_reporting": float(len(fresh)),
        "edl_fleet_straggler_count": float(straggler_count),
    }
    p50s = [v for v in (num(r, "step_p50_ms") for r in fresh)
            if v is not None and v > 0.0]
    if p50s:
        out["edl_fleet_step_p50_ms_median"] = round(_median(p50s), 3)
    if todo_tasks is not None and int(alive_workers or 0) > 0:
        # backlog PER WORKER is undefined with zero alive workers (all
        # churning mid-poll): emitting todo/1 there would hand the
        # autoscaler's grow rule a fake spike exactly when the fleet is
        # least able to absorb an action — absence reads as no-data and
        # the rules (and the autoscaler) hold position instead
        out["edl_fleet_backlog_per_worker"] = round(
            float(todo_tasks) / int(alive_workers), 3)
    fracs = []
    for r in fresh:
        total = sum(num(r, k) or 0.0 for k in _PHASE_KEYS)
        if total > 0:
            fracs.append((num(r, "phase_data_wait_ms") or 0.0) / total)
    if fracs:
        out["edl_fleet_data_wait_frac"] = round(_median(fracs), 4)
    for key, series in (
        ("emb_pull_p99_ms", "edl_fleet_emb_pull_p99_ms"),
        ("emb_read_p99_ms", "edl_fleet_emb_read_p99_ms"),
        ("emb_hot_id_share", "edl_fleet_emb_hot_id_share"),
        ("emb_shard_imbalance", "edl_fleet_emb_shard_imbalance"),
    ):
        vals = [v for v in (num(r, key) for r in fresh) if v is not None]
        if vals:
            # the WORST reporter: alerting on the max is what catches one
            # melting owner in an otherwise-healthy fleet
            out[series] = round(max(vals), 4)
    hit_rates = [v for v in (num(r, "emb_cache_hit_rate") for r in fresh)
                 if v is not None]
    if hit_rates:
        # worst here is the MINIMUM: one worker whose hot set migrated
        # out from under its cache must not hide behind the fleet's
        # still-warm average (the embedding_cache_hit_collapse rule
        # reads this series). Absent when no worker runs a cache — the
        # rule sees "no data" and stays quiet, never a fake zero.
        out["edl_fleet_emb_cache_hit_rate"] = round(min(hit_rates), 4)
    for key, series in (
        # data-plane degradation shares (ISSUE 19): worst reporter, so
        # one worker riding the degraded ladder (or falling back from
        # its shm ring to gRPC fleet-wide) is visible even while the
        # fleet average looks clean
        ("emb_degraded_share", "edl_fleet_emb_degraded_share"),
        ("emb_shm_fallback_share", "edl_fleet_emb_shm_fallback_share"),
    ):
        vals = [v for v in (num(r, key) for r in fresh) if v is not None]
        if vals:
            out[series] = round(max(vals), 4)
    return out


# ---------------------------------------------------------------------- #
# process singleton (master/worker/cohort share one store per process;
# the http endpoint falls back to it when none is wired explicitly)

_STORE: Optional[TimeSeriesStore] = None
_STORE_LOCK = threading.Lock()


def get_store() -> TimeSeriesStore:
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            _STORE = TimeSeriesStore()
        return _STORE


def history_path_for(cfg, role: str) -> Optional[str]:
    """Where a JobConfig implies metrics_history.jsonl should land:
    `<summary_dir|checkpoint_dir>/timeseries/<role>/metrics_history.jsonl`
    (None = memory-only)."""
    base = getattr(cfg, "summary_dir", "") or getattr(
        cfg, "checkpoint_dir", "")
    if not base:
        return None
    slug = (role or "proc").replace("/", "_").replace(" ", "_")
    return os.path.join(base, "timeseries", slug, HISTORY_BASENAME)


def configure_from_config(cfg, role: str) -> TimeSeriesStore:
    """Entrypoint helper (master/worker/cohort): point the process store
    at the job's history location and cadence."""
    store = get_store()
    store.configure(
        history_path=history_path_for(cfg, role) or "",
        interval_s=getattr(cfg, "timeseries_interval_s", None),
        capacity=getattr(cfg, "timeseries_samples", None),
    )
    return store


def reset_for_tests() -> None:
    global _STORE
    with _STORE_LOCK:
        _STORE = None
