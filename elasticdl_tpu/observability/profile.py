"""Always-on step profiler: per-step phase attribution + memory watermarks.

The health layer (PR 6) can say a worker is slow; nothing says WHY. This
module attributes each train step's wall time to phases —

    data_wait   blocking on the input pipeline (reader/parse/shard fill;
                the prefetcher times its source pulls here)
    h2d         host->device transfer dispatch (prefetcher `device_put` /
                cohort global-batch assembly)
    compute     the step dispatch + device compute (the worker's timed
                region, which ends in the scalar readback)
    handoff     rescale/reform work landing on the step path (live state
                handoff, drained-batch requeues)

— and tracks host/device memory watermarks. Always on: the cost per step
is a few perf_counter reads and float adds under a leaf lock (bench.py's
`obs_overhead` leg gates it at <= 2% median step time).

Exports:

- gauges `edl_step_phase_seconds{phase=...}` (rolling per-step mean over
  the window) and `edl_mem_host_rss_mb` / `edl_mem_device_peak_mb`
  (watermarks, refreshed at snapshot time — never per step);
- `snapshot()`: the compact dict that rides the existing heartbeat stats
  payload (observability/health.py), so the master's ClusterHealth sees
  *why* a straggler is slow, not just that it is;
- flight-bundle integration: FlightRecorder.bundle() embeds the snapshot.

Stdlib-only at import; the device-memory probe lazily asks jax (guarded —
absence degrades to host-only watermarks).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterable, Iterator, Optional

from elasticdl_tpu.observability.registry import default_registry

#: the phase vocabulary (snapshot keys are phase_<name>_ms)
PHASES = ("data_wait", "h2d", "compute", "handoff")

#: rolling window (steps) the per-phase means are computed over
WINDOW_DEFAULT = 128

_reg = default_registry()
_PHASE_S = _reg.gauge(
    "edl_step_phase_seconds",
    "rolling per-step mean wall time attributed to each step phase",
    labels=("phase",))
_MEM_HOST = _reg.gauge(
    "edl_mem_host_rss_mb", "host RSS high-water mark (MB)")
_MEM_DEV = _reg.gauge(
    "edl_mem_device_peak_mb",
    "device memory high-water mark (MB; 0 when the backend exposes none)")


#: profiler phase -> goodput-ledger category (observability/goodput.py).
#: `handoff` is deliberately ABSENT: rescale seconds are attributed at
#: the rescale sites themselves (with settle/handoff/compile sub-buckets)
#: and teeing the profiler's handoff too would double-bill them.
PHASE_TO_GOODPUT = {
    "data_wait": "data_wait",
    "h2d": "h2d",
    "compute": "train_compute",
}


class StepProfiler:
    """Accumulate phase seconds into the CURRENT step, roll them into the
    window at `step_done()`. Thread-safe (heartbeat threads snapshot while
    the train loop observes); the lock is a LEAF lock.

    `ledger` (a goodput.GoodputLedger) receives a tee of every phase add
    through PHASE_TO_GOODPUT — the goodput ledger's train/data/h2d
    attribution costs no second timer on the hot path. The process
    singleton (`get_profiler`) wires the process ledger; direct
    constructions opt in explicitly (bench.py's obs_overhead ON leg
    does, so the tee's cost stays inside the measured <=2% gate)."""

    def __init__(self, window: int = WINDOW_DEFAULT, ledger=None):
        self._ledger = ledger
        self._lock = threading.Lock()
        self._acc: Dict[str, float] = {}                 # guarded_by: _lock
        # per-phase rolling windows with maintained sums (mean is O(1))
        self._win: Dict[str, "deque[float]"] = {         # guarded_by: _lock
            p: deque(maxlen=window) for p in PHASES
        }
        self._sums: Dict[str, float] = {p: 0.0 for p in PHASES}  # guarded_by: _lock
        self._steps = 0                                  # guarded_by: _lock
        self._host_peak_mb = 0.0                         # guarded_by: _lock
        self._dev_peak_mb = 0.0                          # guarded_by: _lock

    # ------------------------------------------------------------------ #
    # hot path

    def add(self, phase: str, seconds: float) -> None:
        """Accumulate `seconds` into the current step's `phase` bucket
        (phases outside PHASES are accepted but dropped at step_done —
        bounded keys keep the heartbeat payload inside its size budget)."""
        if seconds <= 0:
            return
        with self._lock:
            self._acc[phase] = self._acc.get(phase, 0.0) + seconds
        if self._ledger is not None:
            category = PHASE_TO_GOODPUT.get(phase)
            if category is not None:
                self._ledger.add(category, seconds)

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def step_done(self, steps: int = 1) -> None:
        """Close the current step (or group of `steps` steps — grouped
        dispatch normalizes to per-step values so grouped and single-step
        workers report comparably) into the rolling windows."""
        n = max(1, int(steps))
        with self._lock:
            acc, self._acc = self._acc, {}
            self._steps += n
            for phase in PHASES:
                v = acc.pop(phase, 0.0) / n
                win = self._win[phase]
                if len(win) == win.maxlen:
                    self._sums[phase] -= win[0]
                win.append(v)
                self._sums[phase] += v
            # leftovers under non-standard keys are dropped (see add())
        for phase in PHASES:
            _PHASE_S.set(self._mean(phase), phase=phase)

    def _mean(self, phase: str) -> float:
        with self._lock:
            win = self._win[phase]
            return self._sums[phase] / len(win) if win else 0.0

    # ------------------------------------------------------------------ #
    # watermarks (snapshot cadence, never per step)

    def update_memory(self) -> None:
        """Refresh host/device memory watermarks. Best-effort: the host
        side is stdlib `resource` (ru_maxrss), the device side asks jax's
        per-device `memory_stats()` when the backend exposes it."""
        host_mb = 0.0
        try:
            import resource

            ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # linux reports KB, macOS bytes; normalize to MB
            host_mb = ru / 1024.0 if os.uname().sysname != "Darwin" \
                else ru / (1024.0 * 1024.0)
        except Exception:
            # no resource module / exotic platform — host watermark stays 0:
            # edl-lint: disable=EDL303
            pass
        dev_mb = 0.0
        try:
            import sys

            jax = sys.modules.get("jax")   # never IMPORT jax from here —
            if jax is not None:            # only read it if the process did
                for d in jax.local_devices():
                    stats = getattr(d, "memory_stats", lambda: None)()
                    if stats:
                        dev_mb += float(
                            stats.get("peak_bytes_in_use",
                                      stats.get("bytes_in_use", 0))
                        ) / (1024.0 * 1024.0)
        except Exception:
            # a backend without memory_stats degrades to host-only:
            # edl-lint: disable=EDL303
            dev_mb = 0.0
        with self._lock:
            self._host_peak_mb = max(self._host_peak_mb, host_mb)
            self._dev_peak_mb = max(self._dev_peak_mb, dev_mb)
            host_peak, dev_peak = self._host_peak_mb, self._dev_peak_mb
        _MEM_HOST.set(host_peak)
        _MEM_DEV.set(dev_peak)

    # ------------------------------------------------------------------ #

    def snapshot(self, update_memory: bool = True) -> Dict[str, Any]:
        """The compact per-process profile row the heartbeat payload (and
        the flight bundle) carries: per-step phase means (ms) for phases
        with data, plus the memory watermarks."""
        if update_memory:
            self.update_memory()
        out: Dict[str, Any] = {}
        with self._lock:
            steps = self._steps
            for phase in PHASES:
                win = self._win[phase]
                if win and self._sums[phase] > 0:
                    out[f"phase_{phase}_ms"] = round(
                        1e3 * self._sums[phase] / len(win), 3
                    )
            host_peak, dev_peak = self._host_peak_mb, self._dev_peak_mb
        if steps:
            out["profiled_steps"] = steps
        if host_peak:
            out["mem_host_mb"] = round(host_peak, 1)
        if dev_peak:
            out["mem_dev_mb"] = round(dev_peak, 1)
        return out

    def reset(self) -> None:
        with self._lock:
            self._acc = {}
            for p in PHASES:
                self._win[p].clear()
                self._sums[p] = 0.0
            self._steps = 0
            self._host_peak_mb = self._dev_peak_mb = 0.0


def timed_iter(iterable: Iterable, profiler: "StepProfiler",
               phase: str = "data_wait") -> Iterator:
    """Yield from `iterable`, attributing each next() wait to `phase` —
    the grouped-dispatch paths' data-wait instrumentation (the prefetcher
    self-times on the k == 1 paths)."""
    it = iter(iterable)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        finally:
            profiler.add(phase, time.perf_counter() - t0)
        yield item


# ---------------------------------------------------------------------- #
# process singleton (worker/cohort/prefetcher all feed the same profile)

_PROFILER: Optional[StepProfiler] = None
_PROFILER_LOCK = threading.Lock()


def get_profiler() -> StepProfiler:
    global _PROFILER
    with _PROFILER_LOCK:
        if _PROFILER is None:
            from elasticdl_tpu.observability import goodput

            # the process profiler tees phase adds into the process
            # goodput ledger: one instrumentation site, two consumers
            _PROFILER = StepProfiler(ledger=goodput.get_ledger())
        return _PROFILER


def reset_for_tests() -> None:
    global _PROFILER
    with _PROFILER_LOCK:
        _PROFILER = None
