"""Span/trace layer for elastic lifecycle events.

One resize should read as ONE timeline: the master's announce/quiesce/
teardown/spawn phases and every worker's compile/handoff/requeue work,
joined by a shared trace id. The pieces:

- `span(name, **attrs)`: context manager; emits one JSONL record on exit
  with wall-clock start, duration, role, world version, trace/span/parent
  ids, and the given attributes. Spans nest through a `contextvars`
  context, so they follow the opening thread (gRPC handler threads get
  their context from `adopt`).
- `event(name, **attrs)`: a point-in-time record (task lease transitions,
  retry decisions, breaker flips) — same schema, no duration.
- propagation: `rpc_metadata()` returns the active (trace id, span id) as
  gRPC metadata pairs; the servicer side re-enters them via `adopt(...)`.
  For master->worker flows with no live RPC (a reform announcement), the
  trace id rides the membership signal file (`trace_id` field) and
  workers adopt it from there.

Records land in `trace.jsonl` (configured path) AND in a bounded
in-memory buffer (`get_tracer().records`) so tests and the bench can read
spans without filesystem coupling. With no configure() call everything
still works — records just stay in memory.

Schema (one JSON object per line):

    {"kind": "span"|"event", "name": ..., "trace_id": ..., "span_id": ...,
     "parent_id": ..., "role": ..., "world_version": ..., "ts": <wall s>,
     "dur_ms": <span only>, "error": <repr, spans that raised>, ...attrs}
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple

#: gRPC metadata keys the trace context rides on (lowercase per gRPC spec)
TRACE_ID_KEY = "edl-trace-id"
SPAN_ID_KEY = "edl-span-id"

#: bounded in-memory record buffer (tests/bench read this)
BUFFER_RECORDS = 4096

_ctx: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = (
    contextvars.ContextVar("edl_trace_ctx", default=None)
)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:8]


class Span:
    """Handle yielded by `span(...)`: lets the body attach attributes."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)


class Tracer:
    """Process-local span recorder. Thread-safe; write failures disable the
    file sink (never the caller) — tracing is strictly best-effort."""

    def __init__(self):
        self._lock = threading.Lock()
        self._path: Optional[str] = None
        self._file = None
        self.role = ""
        self._world_version = 0
        self.records: "deque[dict]" = deque(maxlen=BUFFER_RECORDS)
        # record sinks (the flight recorder's full-fidelity ring rides
        # here): called per emitted record, under the tracer lock — a sink
        # must be CHEAP and leaf-locked only, and must never raise at us
        self._sinks: List = []

    # ------------------------------------------------------------------ #
    # configuration

    def configure(self, path: Optional[str] = None,
                  role: Optional[str] = None,
                  world_version: Optional[int] = None) -> None:
        """(Re)point the tracer. `path` opens (append) the JSONL sink —
        parent directories are created; an unopenable path logs once via
        the record buffer and stays memory-only."""
        with self._lock:
            if role is not None:
                self.role = role
            if world_version is not None:
                self._world_version = int(world_version)
            if path is not None and (path != self._path
                                     or self._file is None):
                self._close_locked()
                self._path = path
                try:
                    os.makedirs(
                        os.path.dirname(os.path.abspath(path)), exist_ok=True
                    )
                    # reconfigure path (boot / scenario swap), and the
                    # tracer lock is a leaf — no control-plane lock is
                    # ever held over configure():
                    # edl-lint: disable=EDL103
                    self._file = open(path, "a", encoding="utf-8")
                except OSError:
                    self._file = None
                    self._path = None

    def set_world_version(self, version: int) -> None:
        with self._lock:
            self._world_version = int(version)

    @property
    def world_version(self) -> int:
        with self._lock:
            return self._world_version

    @property
    def path(self) -> Optional[str]:
        with self._lock:
            return self._path

    # ------------------------------------------------------------------ #
    # emission

    def add_sink(self, fn) -> None:
        """Subscribe `fn(record_dict)` to every emitted record (the flight
        recorder's ring). Runs under the tracer lock: keep it to a leaf-
        locked append; exceptions are swallowed (emission is best-effort
        for sinks exactly as for the file)."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def _emit(self, rec: dict) -> None:
        with self._lock:
            rec.setdefault("role", self.role)
            rec.setdefault("world_version", self._world_version)
            self.records.append(rec)
            for sink in self._sinks:
                try:
                    sink(rec)
                except Exception:
                    # a broken sink must not cost the span (or the file
                    # sink below): edl-lint: disable=EDL303
                    continue
            if self._file is not None:
                try:
                    self._file.write(json.dumps(rec) + "\n")
                    self._file.flush()
                except (OSError, ValueError):
                    # ValueError: write to a closed file (teardown races)
                    self._file = None

    @contextmanager
    def span(self, name: str, *, trace_id: Optional[str] = None,
             parent_id: Optional[str] = None, **attrs) -> Iterator[Span]:
        parent = _ctx.get()
        tid = trace_id or (parent[0] if parent else new_trace_id())
        pid = parent_id if parent_id is not None else (
            parent[1] if parent and not trace_id else None
        )
        # an explicit trace_id starts/joins a foreign trace: the ambient
        # parent only applies when it belongs to the same trace
        if trace_id and parent and parent[0] == trace_id and parent_id is None:
            pid = parent[1]
        sid = new_span_id()
        handle = Span(name, tid, sid, pid, dict(attrs))
        token = _ctx.set((tid, sid))
        t_wall = time.time()
        t0 = time.perf_counter()
        error: Optional[str] = None
        try:
            yield handle
        except BaseException as e:
            error = repr(e)
            raise
        finally:
            _ctx.reset(token)
            rec = {
                "kind": "span",
                "name": name,
                "trace_id": tid,
                "span_id": sid,
                "parent_id": pid,
                "ts": t_wall,
                "dur_ms": round(1e3 * (time.perf_counter() - t0), 3),
            }
            if error is not None:
                rec["error"] = error
            rec.update(handle.attrs)
            self._emit(rec)

    def event(self, name: str, *, trace_id: Optional[str] = None, **attrs):
        parent = _ctx.get()
        tid = trace_id or (parent[0] if parent else None)
        rec = {
            "kind": "event",
            "name": name,
            "trace_id": tid,
            "parent_id": parent[1] if parent else None,
            "ts": time.time(),
        }
        rec.update(attrs)
        self._emit(rec)

    # ------------------------------------------------------------------ #

    @contextmanager
    def scoped(self, path: Optional[str] = None,
               role: Optional[str] = None,
               world_version: Optional[int] = None) -> Iterator["Tracer"]:
        """Temporarily repoint the tracer (file sink, role, world
        version) and restore EVERYTHING on exit — including the
        in-memory ring's prior contents. A simulation can flood
        thousands of spans through the real stack inside this block
        without leaving the process tracer full (a full ring makes
        every later `records[start:]` slice empty) or wearing the
        simulation's role on subsequent log lines."""
        with self._lock:
            prev_role = self.role
            prev_wv = self._world_version
            prev_path = self._path
            prev_had_file = self._file is not None
            prev_records = list(self.records)
        self.configure(path=path, role=role, world_version=world_version)
        try:
            yield self
        finally:
            with self._lock:
                self._close_locked()
                self._path = None
            if prev_path is not None and prev_had_file:
                self.configure(path=prev_path)
            with self._lock:
                self._path = prev_path
                self.role = prev_role
                self._world_version = prev_wv
                self.records.clear()
                self.records.extend(prev_records)

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._file is not None:
            try:
                self._file.flush()
                # teardown flush of the leaf tracer lock (spans only
                # buffered-write on the hot path; fsync happens once, at
                # close/reconfigure): edl-lint: disable=EDL103
                os.fsync(self._file.fileno())
            except (OSError, ValueError):
                pass
            try:
                self._file.close()
            except (OSError, ValueError):
                pass
            self._file = None


# ---------------------------------------------------------------------- #
# module-level singleton + context plumbing

_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def configure(path: Optional[str] = None, role: Optional[str] = None,
              world_version: Optional[int] = None) -> Tracer:
    _TRACER.configure(path=path, role=role, world_version=world_version)
    return _TRACER


def span(name: str, **kw):
    return _TRACER.span(name, **kw)


def event(name: str, **kw) -> None:
    _TRACER.event(name, **kw)


def set_world_version(version: int) -> None:
    _TRACER.set_world_version(version)


def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the active span, or None."""
    return _ctx.get()


def current_trace_id() -> Optional[str]:
    ctx = _ctx.get()
    return ctx[0] if ctx else None


def rpc_metadata() -> Tuple[Tuple[str, str], ...]:
    """gRPC metadata pairs carrying the active trace context ((), when no
    span is open — callers skip the metadata kwarg entirely then)."""
    ctx = _ctx.get()
    if ctx is None:
        return ()
    return ((TRACE_ID_KEY, ctx[0]), (SPAN_ID_KEY, ctx[1]))


@contextmanager
def adopt(trace_id: str, parent_span_id: Optional[str] = None):
    """Enter a foreign trace context (the server side of an RPC hop, a
    worker picking up the master's reform trace id): spans opened inside
    join `trace_id` under `parent_span_id`."""
    token = _ctx.set((trace_id, parent_span_id or ""))
    try:
        yield
    finally:
        _ctx.reset(token)


def context_for_logs() -> Dict[str, object]:
    """What the JSON log formatter stamps on every record (log_utils pulls
    this through a registered provider — no import cycle)."""
    ctx = _ctx.get()
    out: Dict[str, object] = {
        "role": _TRACER.role,
        "world_version": _TRACER.world_version,
    }
    if ctx is not None:
        out["trace_id"] = ctx[0]
        out["span_id"] = ctx[1]
    return out


# log records share the trace context (EDL_LOG_JSON joins on trace_id)
from elasticdl_tpu.common import log_utils as _log_utils  # noqa: E402

_log_utils.set_context_provider(context_for_logs)


# ---------------------------------------------------------------------- #
# trace analysis helpers (bench / tests)


def spans_for_trace(records, trace_id: str) -> List[dict]:
    """Span records of one trace, in emission (i.e. span-END) order."""
    return [
        r for r in records
        if r.get("kind") == "span" and r.get("trace_id") == trace_id
    ]


def phase_durations(records, trace_id: str,
                    prefix: str = "phase.") -> Dict[str, float]:
    """{phase_name: seconds} for `prefix`-named spans of one trace — the
    bench's per-phase recovery breakdown (compile / handoff / settle)."""
    out: Dict[str, float] = {}
    for r in spans_for_trace(records, trace_id):
        name = r["name"]
        if name.startswith(prefix):
            out[name[len(prefix):]] = round(
                out.get(name[len(prefix):], 0.0) + r["dur_ms"] / 1e3, 6
            )
    return out


def trace_path_for(trace_dir: str, summary_dir: str, role: str
                   ) -> Optional[str]:
    """The per-role trace.jsonl path a JobConfig implies ("" trace_dir
    derives <summary_dir>/trace; "off" disables the file sink)."""
    if (trace_dir or "").lower() == "off":
        return None
    base = trace_dir or (
        os.path.join(summary_dir, "trace") if summary_dir else ""
    )
    if not base:
        return None
    return os.path.join(base, role, "trace.jsonl")


def configure_from_config(cfg, role: str,
                          world_version: Optional[int] = None) -> Tracer:
    """Entrypoint helper: point the process tracer at the job's trace dir
    and stamp the role (master / worker-N / cohort-N)."""
    path = trace_path_for(
        getattr(cfg, "trace_dir", ""), getattr(cfg, "summary_dir", ""), role
    )
    if world_version is None:
        try:
            world_version = int(os.environ.get("EDL_WORLD_VERSION", "0") or 0)
        except ValueError:
            world_version = 0
    return configure(path=path, role=role, world_version=world_version)


def read_trace_file(path: str) -> List[dict]:
    """Parse a trace.jsonl (tolerating a truncated last line — the writer
    may have been killed mid-record)."""
    out: List[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return []
    return out
