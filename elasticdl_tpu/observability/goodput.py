"""Fleet goodput ledger: exhaustive wall-clock and wasted-work attribution.

The cluster can see (step profiler), chart (time series), and page
(alert engine) — but nothing totals the bill: *of all the chip-seconds
and records we paid for, what fraction was productive training?* This
module answers that, at three scopes:

- **`GoodputLedger`** (per process): attributes every wall-clock second
  of a worker's life — from ledger construction on — to exactly ONE of

      train_compute     the jitted step (dispatch + device compute)
      data_wait         blocked on the input pipeline
      h2d               host->device transfer / global-batch assembly
      emb_pull_blocked  embedding-tier pulls blocking the step
      rescale           resize work, with settle/handoff/compile
                        sub-buckets (cohort world formation included)
      lease_wait        idle — polling an empty task queue
      reconnect         master unreachable / generation-fence window
                        (boot-register retries, re-register handshakes)
      overhead          the residual, so the categories ALWAYS sum to
                        wall clock — the same total-attribution
                        invariant the trace analyzer's critical path
                        enforces (phase sum == wall by construction)

  The clock is `time.monotonic` (never `time.time`: an NTP step would
  corrupt the ledger — edl-lint EDL406 enforces this tree-wide). The
  hot-path cost is the step profiler's: the profiler tees its phase
  adds into the ledger (`observability/profile.py`), so no new timer
  runs per step; rescale/lease_wait/reconnect/emb_pull sites add a
  `phase()` context each at task/resize granularity.

- **wasted work** (master side, fed from the dispatcher + journal):
  records whose training must be repeated or whose completed training
  was discarded. Every entry is `(reason, task_id, records)`, journaled
  per task (`wasted_work` records in the control-plane journal) so a
  master restart replays the bill intact. Reasons:

      worker_died / lease_expired   the lease's span re-trains whole
      failure_retry                 ran once, result discarded, re-runs
      crash_requeue                 the successor's conservative replay
                                    requeue (journaled at takeover)
      fenced_report                 a completed report rejected by the
                                    generation fence — finished work
                                    discarded (claimed records)
      stale_report                  a report from a superseded lease
                                    holder — its work is discarded
      drain_requeue                 a preemption drain's remainder,
                                    requeued for another lease

  `fenced_report`/`stale_report` evidence work that WAS done and then
  thrown away; the requeue reasons bill the re-training. The two views
  can overlap on the same records (the fenced span is usually also the
  requeued span) — per-reason buckets keep the overlap inspectable.

- **`FleetGoodput`** (master): rolls per-worker ledger payloads (riding
  the existing heartbeat stats channel as `gp_*` keys) plus the
  dispatcher's wasted-work totals into the fleet picture — fleet
  goodput fraction, per-category fleet seconds, wasted-records total
  and ratio — exported as `edl_goodput_*` gauges, sampled into the
  time-series store (the input of the `goodput_burn` /
  `wasted_work_ratio` default alert rules), served at `GET /goodput`,
  and summarized by the incident CLI.

Stdlib-only, jax-free, strictly best-effort, like the rest of the
package. See docs/observability.md ("Goodput ledger").
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

from elasticdl_tpu.observability.registry import default_registry

#: the category vocabulary; `overhead` is derived (wall - attributed),
#: never added directly
CATEGORIES = (
    "train_compute", "data_wait", "h2d", "emb_pull_blocked",
    "rescale", "lease_wait", "reconnect", "overhead",
)

#: rescale sub-buckets (mirror the resize trace's phase vocabulary)
RESCALE_SUBS = ("settle", "handoff", "compile")

#: heartbeat-payload key prefix; the payload carries `gp_wall_s` plus
#: one `gp_<category>_s` per category with nonzero seconds (overhead
#: included, so the master can re-check the sum without re-deriving)
PAYLOAD_PREFIX = "gp_"

_PAYLOAD_KEYS = {
    "train_compute": "gp_train_compute_s",
    "data_wait": "gp_data_wait_s",
    "h2d": "gp_h2d_s",
    "emb_pull_blocked": "gp_emb_pull_blocked_s",
    "rescale": "gp_rescale_s",
    "lease_wait": "gp_lease_wait_s",
    "reconnect": "gp_reconnect_s",
    "overhead": "gp_overhead_s",
}

#: wasted-work reasons whose records are RE-TRAINED spans (the requeue
#: bill); fenced/stale reports evidence discarded completed work instead
REQUEUE_REASONS = (
    "worker_died", "lease_expired", "failure_retry", "crash_requeue",
    "drain_requeue",
)
REPORT_REASONS = ("fenced_report", "stale_report")
WASTED_REASONS = REQUEUE_REASONS + REPORT_REASONS

_reg = default_registry()
_GP_SECONDS = _reg.gauge(
    "edl_goodput_seconds",
    "cumulative wall-clock seconds this process attributes to each "
    "goodput category (categories sum to wall clock)",
    labels=("category",))
_GP_FRACTION = _reg.gauge(
    "edl_goodput_fraction",
    "this process's train_compute seconds / wall-clock seconds")
def _fleet_gauges():
    """The master-side rollup gauges, registered LAZILY (idempotent) at
    first real rollup instead of at import: an unlabelled registered-but-
    never-set gauge snapshots as 0, and a boot-time
    `edl_goodput_fleet_fraction = 0` would (a) fire the goodput_burn rule
    spuriously on every fresh master — 0 must read as "no data", not
    "zero goodput" — and (b) pollute every WORKER's /metrics with
    fleet-scoped zeros merely for importing this module."""
    return (
        _reg.gauge(
            "edl_goodput_fleet_seconds",
            "fleet-total worker seconds per goodput category (master "
            "rollup over heartbeat ledger payloads)",
            labels=("category",)),
        _reg.gauge(
            "edl_goodput_fleet_wall_seconds",
            "fleet-total worker wall-clock seconds with a goodput ledger"),
        _reg.gauge(
            "edl_goodput_fleet_fraction",
            "fleet goodput fraction: train_compute / wall across "
            "reporters"),
    )


def _wasted_gauges():
    """Lazy for the same reason as _fleet_gauges (master-only scope)."""
    return (
        _reg.gauge(
            "edl_goodput_wasted_records",
            "authoritative wasted-record total (journal-replayed; "
            "survives master restart)"),
        _reg.gauge(
            "edl_goodput_wasted_ratio",
            "wasted records / (completed + wasted) training records "
            "(lifetime-cumulative)"),
    )


# NOTE deliberately NO registry gauges for the windowed
# `edl_goodput_fleet_recent_fraction` / `edl_goodput_recent_wasted_ratio`
# series the burn rules watch: they reach the time-series store ONLY
# through FleetGoodput.series() (the sampler extra), so a rollup that
# SKIPS the sample (reporter churn, no fleet data yet) produces a true
# data gap. A gauge would defeat both protections at once — a
# registered-but-never-set gauge snapshots as 0 ("zero goodput" instead
# of "no data"), and a set-once gauge would repeat its stale pre-churn
# value into every later sample.


_WASTED_EVENTS_C = _reg.counter(
    "edl_goodput_wasted_events_total",
    "wasted-work ledger entries by reason (live; restart resets)",
    labels=("reason",))
_WASTED_RECORDS_C = _reg.counter(
    "edl_goodput_wasted_records_total",
    "wasted records by reason (live; restart resets — the gauge above "
    "is the replay-durable total)",
    labels=("reason",))


def record_wasted(reason: str, records: int) -> None:
    """Live metric side of one wasted-work entry (the dispatcher calls
    this next to journaling it). Reason values come from the bounded
    WASTED_REASONS vocabulary at every call site."""
    _WASTED_EVENTS_C.inc(reason=reason)
    if records > 0:
        _WASTED_RECORDS_C.inc(records, reason=reason)


class GoodputLedger:
    """Per-process wall-clock attribution with a total-sum invariant.

    Thread-safe: the train loop and prefetcher attribute phases (via the
    step profiler's tee), the heartbeat thread snapshots. The lock is a
    LEAF lock. The clock is monotonic — wall time here is *elapsed life
    since the ledger started*, immune to NTP steps (EDL406)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._acc: Dict[str, float] = {               # guarded_by: _lock
            c: 0.0 for c in CATEGORIES if c != "overhead"
        }
        self._rescale_sub: Dict[str, float] = {       # guarded_by: _lock
            s: 0.0 for s in RESCALE_SUBS
        }

    # ------------------------------------------------------------------ #
    # hot path

    def add(self, category: str, seconds: float,
            sub: Optional[str] = None) -> None:
        """Attribute `seconds` to `category` (unknown categories are
        dropped — the vocabulary is the payload schema, and a typo'd
        category must not silently grow it). `sub` refines `rescale`
        into its settle/handoff/compile sub-buckets."""
        if seconds <= 0 or category == "overhead":
            return
        with self._lock:
            if category not in self._acc:
                return
            self._acc[category] += seconds
            if category == "rescale" and sub in self._rescale_sub:
                self._rescale_sub[sub] += seconds

    @contextmanager
    def phase(self, category: str, sub: Optional[str] = None) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(category, self._clock() - t0, sub=sub)

    # ------------------------------------------------------------------ #

    def snapshot(self, now: Optional[float] = None,
                 update_metrics: bool = False) -> Dict:
        """The full attribution: every category (overhead = residual,
        clamped at 0), the rescale sub-buckets, wall clock, and the
        goodput fraction. `overattributed_s` surfaces any double-
        attribution (explicit categories summing past wall) instead of
        hiding it in a negative residual — the bench's 1% gate reads
        it."""
        now = self._clock() if now is None else now
        with self._lock:
            wall = max(0.0, now - self._t0)
            acc = dict(self._acc)
            subs = dict(self._rescale_sub)
        attributed = sum(acc.values())
        overhead = wall - attributed
        categories = {c: round(acc[c], 6) for c in acc}
        categories["overhead"] = round(max(0.0, overhead), 6)
        out = {
            "wall_s": round(wall, 6),
            "categories": categories,
            "rescale_phases": {s: round(v, 6) for s, v in subs.items()},
            "goodput_fraction": (
                round(acc["train_compute"] / wall, 6) if wall > 0 else 0.0
            ),
            "overattributed_s": round(max(0.0, -overhead), 6),
        }
        if update_metrics:
            for c, v in categories.items():
                # keys come from the module-constant CATEGORIES
                # vocabulary (add() drops anything else), so the label
                # set is bounded: edl-lint: disable=EDL405
                _GP_SECONDS.set(v, category=c)
            _GP_FRACTION.set(out["goodput_fraction"])
        return out

    def payload(self, now: Optional[float] = None) -> Dict[str, float]:
        """The compact heartbeat ride-along: `gp_wall_s` + one key per
        category with nonzero seconds (ms-precision rounding keeps the
        JSON small). Also refreshes this process's edl_goodput_* gauges
        — the heartbeat cadence is the snapshot cadence."""
        snap = self.snapshot(now=now, update_metrics=True)
        out: Dict[str, float] = {"gp_wall_s": round(snap["wall_s"], 3)}
        for category, key in _PAYLOAD_KEYS.items():
            v = snap["categories"].get(category, 0.0)
            if v > 0:
                out[key] = round(v, 3)
        return out

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._clock()
            for c in self._acc:
                self._acc[c] = 0.0
            for s in self._rescale_sub:
                self._rescale_sub[s] = 0.0


# ---------------------------------------------------------------------- #
# fleet rollup (master side)


def aggregate_payloads(health_records: List[Dict],
                       stale_after_s: float = 30.0,
                       now: Optional[float] = None) -> Dict:
    """Sum the `gp_*` ledger payloads of workers with FRESH telemetry
    (staleness keyed on the record's wall-clock `updated_at`, same
    contract as the fleet series). Per-worker ledgers are cumulative, so
    the sums are fleet-cumulative seconds. Returns {} when no reporter
    carries a ledger — absence must read as "no data" to the rules, not
    as zero goodput."""
    now = time.time() if now is None else now
    totals = {c: 0.0 for c in CATEGORIES}
    wall = 0.0
    reporters = 0
    for rec in health_records:
        try:
            updated = float(rec.get("updated_at") or 0.0)
        except (TypeError, ValueError):
            continue
        if now - updated > stale_after_s:
            continue
        w = rec.get("gp_wall_s")
        if not isinstance(w, (int, float)) or isinstance(w, bool) or w <= 0:
            continue
        reporters += 1
        wall += float(w)
        for category, key in _PAYLOAD_KEYS.items():
            v = rec.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                totals[category] += float(v)
    if not reporters:
        return {}
    return {
        "reporters": reporters,
        "wall_s": round(wall, 3),
        "categories": {c: round(v, 3) for c, v in totals.items()},
        "goodput_fraction": (
            round(totals["train_compute"] / wall, 6) if wall > 0 else 0.0
        ),
    }


class FleetGoodput:
    """The master's goodput rollup: heartbeat ledger payloads (via
    Membership's health records) + the dispatcher's journal-durable
    wasted-work totals, recomputed every wait poll next to the cluster-
    health scorer. `snapshot()` is cheap and cached (served by /goodput,
    /healthz enrichment, and the incident CLI's health files);
    `series()` feeds the master's time-series sampler — the sensor the
    goodput_burn / wasted_work_ratio default rules read."""

    def __init__(self, membership, dispatcher=None):
        self._membership = membership
        self._dispatcher = dispatcher
        self._lock = threading.Lock()
        self._last: Dict = {"ts": 0.0}                # guarded_by: _lock
        # previous rollup's cumulative sums, for the windowed "recent"
        # series (update() has a single caller — the master's wait loop —
        # so these need no lock of their own)
        self._prev_fleet: Optional[Dict[str, float]] = None
        self._prev_wasted: Optional[Dict[str, int]] = None

    def update(self, now: Optional[float] = None) -> Dict:
        """Recompute the rollup; never raises (wait-loop contract)."""
        try:
            return self._update(now)
        except Exception:
            from elasticdl_tpu.common.log_utils import default_logger

            default_logger(__name__).exception(
                "fleet goodput rollup failed; keeping last")
            return self.snapshot()

    def _update(self, now: Optional[float] = None) -> Dict:
        now = time.time() if now is None else now
        fleet = aggregate_payloads(
            self._membership.health_snapshot(), now=now)
        snap: Dict = {"ts": now, "fleet": fleet}
        if fleet:
            seconds_g, wall_g, fraction_g = _fleet_gauges()
            for c, v in fleet["categories"].items():
                # aggregate_payloads emits exactly the CATEGORIES
                # vocabulary — bounded: edl-lint: disable=EDL405
                seconds_g.set(v, category=c)
            wall_g.set(fleet["wall_s"])
            fraction_g.set(fleet["goodput_fraction"])
            # the windowed fraction: delta train / delta wall since the
            # previous rollup. Reporter churn (a restarted worker resets
            # its cumulative ledger; a dead one leaves the sum) shows up
            # as a negative delta — SKIP the sample then (absence reads
            # as no-data to the rules, which carry active alerts
            # forward) rather than emit garbage.
            prev, self._prev_fleet = self._prev_fleet, {
                "wall": fleet["wall_s"],
                "train": fleet["categories"]["train_compute"],
            }
            if prev is not None:
                dwall = fleet["wall_s"] - prev["wall"]
                dtrain = (
                    fleet["categories"]["train_compute"] - prev["train"]
                )
                if dwall > 1e-9 and dtrain >= 0:
                    fleet["recent_fraction"] = round(
                        min(1.0, dtrain / dwall), 6)
        if self._dispatcher is not None:
            wasted = self._dispatcher.wasted_work()
            snap["wasted"] = wasted
            records_g, ratio_g = _wasted_gauges()
            records_g.set(wasted["wasted_records"])
            ratio_g.set(wasted["wasted_ratio"])
            prev_w, self._prev_wasted = self._prev_wasted, {
                "wasted": wasted["wasted_records"],
                "completed": wasted["records_completed"],
            }
            if prev_w is not None:
                dw = wasted["wasted_records"] - prev_w["wasted"]
                dc = wasted["records_completed"] - prev_w["completed"]
                if dw >= 0 and dc >= 0:
                    # zero activity reads as an honest 0.0 ("no new
                    # waste"), so a stall with an active alert can clear
                    denom = dw + dc
                    wasted["recent_ratio"] = (
                        round(dw / denom, 6) if denom > 0 else 0.0)
        with self._lock:
            self._last = snap
        return snap

    def snapshot(self) -> Dict:
        with self._lock:
            return dict(self._last)

    def series(self) -> Dict[str, float]:
        """Flat series for the master's sampler extra: ONLY the windowed
        recent values, which deliberately have no registry gauge (see
        the module note above _FLEET gauges) — everything cumulative
        already rides the registry snapshot into the same sample, and
        emitting it twice here would be double bookkeeping. A skipped
        rollup emits nothing: absence IS the no-data signal the rules'
        carried-forward semantics key on."""
        snap = self.snapshot()
        out: Dict[str, float] = {}
        fleet = snap.get("fleet") or {}
        if "recent_fraction" in fleet:
            out["edl_goodput_fleet_recent_fraction"] = (
                fleet["recent_fraction"])
        wasted = snap.get("wasted") or {}
        if "recent_ratio" in wasted:
            out["edl_goodput_recent_wasted_ratio"] = (
                wasted["recent_ratio"])
        return out


# ---------------------------------------------------------------------- #
# process singleton (worker/cohort/tier/profiler feed the same ledger;
# the /goodput endpoint falls back to it when none is wired explicitly)

_LEDGER: Optional[GoodputLedger] = None
_LEDGER_LOCK = threading.Lock()


def get_ledger() -> GoodputLedger:
    global _LEDGER
    with _LEDGER_LOCK:
        if _LEDGER is None:
            _LEDGER = GoodputLedger()
        return _LEDGER


def reset_for_tests() -> None:
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = None
