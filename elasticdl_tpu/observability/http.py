"""/metrics + /healthz scrape endpoint (stdlib http.server).

Strictly best-effort and off the hot path: serving runs on daemon threads
(ThreadingHTTPServer), a failed bind or a dead server never takes the
process down, and the `metrics_scrape` fault site lets chaos schedules
abort scrapes (`drop`), slow them (`delay`), or kill the ENDPOINT
(`crash` — the server shuts down; the training process must not notice).

Binding goes through `net.bind_with_retry` for the ephemeral-port case
(the launcher TOCTOU discipline every other server here follows); a
fixed port raises PortBindError so callers can retry or disable.
"""

from __future__ import annotations

import errno
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.common.net import PortBindError, bind_with_retry
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import (
    MetricsRegistry,
    default_registry,
)

logger = default_logger(__name__)

#: fault-injection site fired per scrape request (see common/faults.py)
SCRAPE_FAULT_SITE = "metrics_scrape"

#: env knob for the default servers master/worker start: a port number,
#: "0" = ephemeral (the default), "-1"/"off" = disabled
PORT_ENV = "EDL_METRICS_PORT"


class ObservabilityServer:
    """One /metrics + /healthz endpoint over a registry."""

    #: endpoint -> one-line description: what GET / serves, so an
    #: operator curling a process learns its surface without reading
    #: source (every process serves all of these; master-only state —
    #: alerts, fleet goodput — answers with a disabled/absent marker
    #: elsewhere)
    ENDPOINTS = {
        "/": "this index",
        "/metrics": "Prometheus text: the process metric registry",
        "/healthz": "liveness + role/world-version (master adds "
                    "generation, membership, cluster rollup, alerts, "
                    "fleet goodput)",
        "/timeseries": "recent metric history ring "
                       "(?window=<s>&series=a,b)",
        "/alerts": "alert engine state (active/history/rules; "
                   "disabled off-master)",
        "/goodput": "goodput ledger: per-category wall-clock "
                    "attribution (master adds the fleet rollup + "
                    "wasted-work bill)",
        "/debug/flight": "dump + serve the flight-recorder ring "
                         "(explicit incident trigger)",
    }

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 role: str = "", host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], Dict]] = None,
                 flight=None, timeseries=None, alerts=None,
                 goodput_fn: Optional[Callable[[], Dict]] = None):
        self.registry = registry or default_registry()
        self.role = role
        self.host = host
        # /debug/flight serves (and dumps) this recorder's bundle; None
        # falls back to the process singleton at request time — the
        # recorder may be configured after the server starts
        self.flight = flight
        # /timeseries serves this store's recent window (None falls back
        # to the process singleton — every process has one); /alerts
        # serves the engine's snapshot (masters wire one; elsewhere the
        # endpoint answers with an empty, disabled-marked state)
        self.timeseries = timeseries
        self.alerts = alerts
        # /goodput serves the process ledger's attribution; the master
        # wires goodput_fn to add its FleetGoodput rollup (cached state,
        # never a recompute — same contract as health_fn)
        self.goodput_fn = goodput_fn
        # /healthz enrichment: a dict merged into the response (the master
        # wires generation/alive-count/cluster-rollup here). Best-effort
        # like everything else on this surface — a raising callback marks
        # the response, never 500s it, and the underlying state (e.g. the
        # ClusterHealth rollup) is computed elsewhere: a dead or dying
        # endpoint never blocks health SCORING.
        self.health_fn = health_fn
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #

    def _handler_class(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # never let a slow/half-open scraper pin a handler thread
            timeout = 10

            def do_GET(self):
                fired = faults.check(SCRAPE_FAULT_SITE)
                if fired is not None and fired.action == "drop":
                    # abort the connection with no response — the scraper
                    # sees a reset, training sees nothing
                    self.close_connection = True
                    return
                if fired is not None and fired.action == "crash":
                    # kill the ENDPOINT, not the process: serving is
                    # best-effort; chaos tests assert training continues
                    outer.stop(_from_handler=True)
                    self.close_connection = True
                    return
                if self.path.split("?")[0] == "/":
                    # the index (ISSUE 12 satellite): every mounted
                    # endpoint with a one-line description — no more
                    # reading the source to learn what a process serves
                    payload = {
                        "role": outer.role,
                        "endpoints": dict(outer.ENDPOINTS),
                    }
                    body = (
                        json.dumps(payload, indent=1, sort_keys=True)
                        + "\n"
                    ).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/metrics":
                    body = outer.registry.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/debug/flight":
                    # explicit incident trigger: dump the flight ring (the
                    # atomic file write is best-effort) AND serve the
                    # bundle back. dump()/bundle() copy the ring under its
                    # leaf lock and do file I/O outside it, so a dump in
                    # progress never blocks a concurrent /metrics or
                    # /healthz scrape (satellite-tested).
                    from elasticdl_tpu.observability import (
                        flight as flight_lib,
                    )

                    rec = outer.flight or flight_lib.get_recorder()
                    bundle = rec.bundle(reason="http")
                    bundle["dumped_to"] = rec.dump(
                        reason="http", bundle=bundle
                    )
                    body = (
                        json.dumps(bundle, default=repr) + "\n"
                    ).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/timeseries":
                    # the bounded snapshot ring (observability/
                    # timeseries.py): ?window=<s> bounds the window,
                    # ?series=a,b filters. to_payload copies the ring
                    # under its leaf lock and does the arithmetic
                    # outside, so a scrape never blocks sampling.
                    from urllib.parse import parse_qs, urlsplit

                    from elasticdl_tpu.observability import (
                        timeseries as ts_lib,
                    )

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        window = float(q.get("window", ["300"])[0])
                    except ValueError:
                        window = 300.0
                    wanted = None
                    if q.get("series"):
                        wanted = [
                            s for s in q["series"][0].split(",") if s
                        ]
                    store = outer.timeseries or ts_lib.get_store()
                    payload = store.to_payload(
                        window_s=window, series=wanted)
                    payload["role"] = outer.role
                    body = (
                        json.dumps(payload, default=repr) + "\n"
                    ).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/alerts":
                    # the alert engine's cached state (observability/
                    # alerts.py) — a scrape never triggers an evaluation
                    if outer.alerts is not None:
                        payload = outer.alerts.snapshot()
                    else:
                        payload = {"enabled": False, "active": [],
                                   "history": [], "rules": []}
                    payload["role"] = outer.role
                    body = (
                        json.dumps(payload, default=repr) + "\n"
                    ).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/goodput":
                    # the goodput ledger (observability/goodput.py):
                    # this process's per-category wall-clock attribution
                    # (snapshot copies under the leaf lock, arithmetic
                    # outside), plus — on the master — the cached fleet
                    # rollup and wasted-work bill. Best-effort like
                    # health_fn: a raising fleet callback marks the
                    # response, never 500s it.
                    from elasticdl_tpu.observability import (
                        goodput as goodput_lib,
                    )

                    payload = {
                        "role": outer.role,
                        "ledger": goodput_lib.get_ledger().snapshot(),
                    }
                    if outer.goodput_fn is not None:
                        try:
                            extra = outer.goodput_fn()
                            if isinstance(extra, dict):
                                payload["fleet"] = extra
                        except Exception:
                            # edl-lint: disable=EDL303
                            payload["fleet_error"] = True
                    body = (
                        json.dumps(payload, default=repr) + "\n"
                    ).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/healthz":
                    payload = {
                        "status": "ok",
                        "role": outer.role,
                        "world_version": tracing.get_tracer().world_version,
                        "pid": os.getpid(),
                    }
                    if outer.health_fn is not None:
                        try:
                            extra = outer.health_fn()
                            if isinstance(extra, dict):
                                payload.update(extra)
                        except Exception:
                            # enrichment is advisory; the probe answer
                            # ("the process serves") must still go out:
                            # edl-lint: disable=EDL303
                            payload["health_extra_error"] = True
                    body = (json.dumps(payload) + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # quiet by default
                logger.debug("metrics endpoint: " + fmt, *args)

        return Handler

    def _build(self, port: int) -> ThreadingHTTPServer:
        handler = self._handler_class()
        try:
            srv = ThreadingHTTPServer((self.host, port), handler)
        except OSError as e:
            if e.errno == errno.EADDRINUSE:
                raise PortBindError(
                    f"metrics endpoint lost port {port} to the bind race"
                ) from e
            raise
        srv.daemon_threads = True
        return srv

    def start(self, port: int = 0) -> int:
        """Bind and serve on a daemon thread; returns the bound port.
        port=0 picks an ephemeral port through net.bind_with_retry."""
        if self._server is not None:
            return self.port
        if port == 0:
            self.port, self._server = bind_with_retry(self._build)
        else:
            self._server = self._build(port)
            self.port = port
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="edl-metrics-http",
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "metrics endpoint serving on http://%s:%d/metrics (role %s)",
            self.host, self.port, self.role or "?",
        )
        return self.port

    def stop(self, _from_handler: bool = False) -> None:
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is None:
            return
        if _from_handler:
            # shutdown() deadlocks when called from a handler thread of the
            # same server; hand it to a throwaway thread
            def _kill():
                server.shutdown()
                server.server_close()

            threading.Thread(
                target=_kill, name="edl-metrics-kill", daemon=True
            ).start()
            return
        server.shutdown()
        server.server_close()
        if thread is not None:
            thread.join(timeout=5)

    @property
    def address(self) -> Optional[str]:
        return f"http://{self.host}:{self.port}" if self.port else None


def start_server(role: str = "", port: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 health_fn: Optional[Callable[[], Dict]] = None,
                 timeseries=None, alerts=None,
                 goodput_fn: Optional[Callable[[], Dict]] = None,
                 ) -> Optional[ObservabilityServer]:
    """Best-effort endpoint start for the master/worker entrypoints.
    A set (non-empty) EDL_METRICS_PORT env overrides `port` in BOTH
    directions: it can disable a configured endpoint ("-1"/"off") or
    enable/repoint one the config disabled. Otherwise `port` decides:
    None/0 = ephemeral, < 0 = disabled. Returns None instead of raising —
    observability must never be the reason a job fails to boot."""
    raw = os.environ.get(PORT_ENV)
    if raw is not None and raw.strip():
        raw = raw.strip().lower()
        if raw in ("-1", "off", "disabled", "none"):
            return None
        try:
            port = int(raw)
        except ValueError:
            # a typo'd override must not silently bind a random port the
            # operator's scraper will never find — disable, loudly
            logger.warning(
                "%s=%r is not a port number; metrics endpoint disabled",
                PORT_ENV, raw,
            )
            return None
    if port is None:
        port = 0
    if port < 0:
        return None
    server = ObservabilityServer(
        registry=registry, role=role, health_fn=health_fn,
        timeseries=timeseries, alerts=alerts, goodput_fn=goodput_fn,
    )
    try:
        server.start(port)
    except Exception:
        logger.exception("metrics endpoint failed to start; continuing")
        return None
    return server
