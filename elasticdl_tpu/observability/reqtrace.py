"""Per-request diaries for the embedding data plane, tail-sampled.

The tier can see aggregates (histograms, the goodput ledger, skew
telemetry) but nothing explains an *individual* slow call: when
`emb_read_p99_ms` spikes, the postmortem needs to know whether that
p99 burned its time in budget wait, the hedge race, a breaker verdict,
shm vs gRPC, server-side queueing, the store gather, or the codec.
This module answers that with **request diaries** under **tail-based
sampling**:

- every data-plane call opens a cheap in-memory stage ledger (a
  `Diary`): enter/exit deltas per stage, accumulated on the CALLER
  thread so stage seconds are non-overlapping by construction and sum
  to the call's wall clock (the goodput ledger's total-attribution
  invariant, applied per request — the residual lands in `other`);
- at finish, only diaries that ended **slow** (wall beyond a
  p99-derived per-op threshold), **errored**, or **degraded** are
  retained in a bounded ring; everything else is dropped at O(1) cost
  (a deque append + two counter bumps), which is what keeps the
  bench's `obs_overhead` ≤2% gate honest with diaries ON;
- retained diaries roll up three ways: a per-process
  `edl_emb_p99_attribution_seconds{stage}` decomposition (stages sum
  to the retained wall), a compact `rt_*` heartbeat payload the
  master's fleet series and the dominant-stage-shift alert read, and a
  `diaries` block in flight-recorder bundles that the incident CLI
  renders as `slow_calls` stage waterfalls.

Instrumentation sites call the module-level helpers — `stage()`,
`attribute()`, `event()` — which attribute into the calling thread's
ACTIVE diaries and no-op (one thread-local read) when there are none,
so the tier, the transports, and the server can be instrumented
without threading a diary handle through every signature. Diaries
NEST (the tier opens one per fused read, the transport one per owner
call, on the same thread): the thread-local is a stack and a stage
lands in every open diary, so each keeps its own sum-to-wall
invariant. Hedge worker threads have no active diary by design: their
wire time is the caller's `hedge` wait, and counting both would break
the attribution invariant.

Stdlib-only, jax-free, strictly best-effort, like the rest of the
package. See docs/observability.md ("Request diaries").
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

from elasticdl_tpu.observability.registry import (
    default_registry, quantile_sorted)

#: the stage vocabulary — the diary payload schema. `attribute()` folds
#: unknown names into `other` (a typo'd stage must not grow the label
#: set), and `other` is also where the unattributed residual lands so a
#: diary's stages ALWAYS sum to its wall clock.
STAGES = (
    "dedupe",       # tier-side id unique/partition before the wire
    "budget_wait",  # retry backoff sleeps + deadline-budget acquire
    "breaker",      # breaker verdicts and breaker-blocked waits
    "wire",         # a gRPC (or sim-wire) attempt, caller-side
    "shm",          # a same-host shared-memory ring round-trip
    "hedge",        # waiting on the hedge race after the hedge fired
    "serve_queue",  # server-side: queueing before the store is touched
    "store",        # server-side: the store gather/apply itself
    "codec",        # proto encode/decode + row blob segmentation
    "other",        # the residual — wall minus everything attributed
)

FINISH_STATUSES = ("ok", "error", "degraded")

#: retained-ring default; small — only tails live here
RING_DEFAULT = 256

#: worst retained diaries exported per flight bundle
BUNDLE_SLOW_CALLS = 16

#: per-op wall-clock window the slow threshold derives from
WINDOW = 128

#: finishes per op between threshold recomputes (sorting the window on
#: every finish would cost ~µs on a path budgeted in single µs)
RECALC_EVERY = 32

#: minimum samples before the p99 threshold arms — until then only
#: error/degraded diaries retain (a cold process has no tail yet)
WARMUP = 32

#: threshold floor: guards the armed p99 against microsecond noise
FLOOR_S = float(os.environ.get("EDL_REQTRACE_FLOOR_US", "100")) * 1e-6

_reg = default_registry()
_DIARIES = _reg.counter(
    "edl_emb_reqtrace_diaries_total",
    "data-plane request diaries by outcome (tail-based sampling: "
    "retained_slow / retained_error / retained_degraded / dropped)",
    labels=("outcome",))
_ATTR = _reg.gauge(
    "edl_emb_p99_attribution_seconds",
    "cumulative per-stage seconds over this process's retained (tail) "
    "diaries — stages sum to edl_emb_reqtrace_slow_wall_seconds",
    labels=("stage",))
_SLOW_WALL = _reg.gauge(
    "edl_emb_reqtrace_slow_wall_seconds",
    "cumulative wall-clock seconds of retained diaries (the attribution "
    "gauge's invariant total)")
_THRESHOLD = _reg.gauge(
    "edl_emb_reqtrace_slow_threshold_seconds",
    "current p99-derived slow threshold per diary op",
    labels=("op",))

_TLS = threading.local()
_NULL_CTX = contextlib.nullcontext()


class Diary:
    """One call's stage ledger. Owned by the thread that started it;
    `events` may be appended from helper threads (list.append is
    atomic), stage attribution stays caller-thread-only."""

    __slots__ = ("op", "meta", "t0", "ts", "stages", "events",
                 "status", "detail", "wall_s")

    def __init__(self, op: str, clock, meta: Optional[Dict] = None):
        self.op = op
        self.meta = meta or {}
        self.t0 = clock()
        self.ts = time.time()   # wall-clock, for cross-bundle correlation
        self.stages: Dict[str, float] = {}
        self.events: List[Dict] = []
        self.status = "ok"
        self.detail = ""
        self.wall_s = 0.0

    def add(self, stage: str, seconds: float) -> None:
        if seconds <= 0:
            return
        if stage not in STAGES or stage == "other":
            stage = "other"
        self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    def event(self, name: str, **fields) -> None:
        if len(self.events) < 64:    # bounded — diaries ride bundles
            self.events.append({"name": name, **fields})

    def to_dict(self) -> Dict:
        """The bundle/ring form. Stages are completed with the `other`
        residual here so sum(stages) == wall_s by construction."""
        stages = {s: round(v, 6) for s, v in self.stages.items()}
        attributed = sum(self.stages.values())
        stages["other"] = round(
            stages.get("other", 0.0) + max(0.0, self.wall_s - attributed),
            6)
        known = self.wall_s - stages["other"]
        return {
            "op": self.op,
            "ts": round(self.ts, 6),
            "wall_s": round(self.wall_s, 6),
            "status": self.status,
            "detail": self.detail,
            "stages": stages,
            "known_share": (round(max(0.0, known) / self.wall_s, 6)
                            if self.wall_s > 0 else 0.0),
            "events": list(self.events),
            "meta": dict(self.meta),
        }


# ---------------------------------------------------------------------- #
# caller-thread helpers — the instrumentation surface


def _stack() -> List[Diary]:
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


def current() -> Optional[Diary]:
    s = getattr(_TLS, "stack", None)
    return s[-1] if s else None


def attribute(stage: str, seconds: float) -> None:
    """Attribute `seconds` to `stage` on every open diary of the
    calling thread; no-op (one thread-local read) when none are."""
    s = getattr(_TLS, "stack", None)
    if s:
        for d in s:
            d.add(stage, seconds)


def stage(name: str, clock=time.monotonic):
    """Context manager timing one stage on the thread's open diaries.
    Returns a shared null context when none are active — the disabled
    path allocates nothing."""
    s = getattr(_TLS, "stack", None)
    if not s:
        return _NULL_CTX
    return _StageCtx(tuple(s), name, clock)


class _StageCtx:
    __slots__ = ("_ds", "_name", "_clock", "_t0")

    def __init__(self, ds, name: str, clock):
        self._ds, self._name, self._clock = ds, name, clock

    def __enter__(self):
        self._t0 = self._clock()
        return self

    def __exit__(self, *exc):
        dt = self._clock() - self._t0
        for d in self._ds:
            d.add(self._name, dt)
        return False


def event(name: str, **fields) -> None:
    s = getattr(_TLS, "stack", None)
    if s:
        for d in s:
            d.event(name, **fields)


# ---------------------------------------------------------------------- #


class _OpWindow:
    """Per-op wall-clock window + cached p99-derived threshold."""

    __slots__ = ("walls", "count", "threshold_s", "next_recalc")

    def __init__(self):
        self.walls = deque(maxlen=WINDOW)
        self.count = 0
        self.threshold_s: Optional[float] = None   # None until armed
        self.next_recalc = WARMUP


class DiaryRecorder:
    """Process-wide diary sink: tail-based retention into a bounded
    ring, cumulative per-stage attribution, heartbeat payload, flight-
    bundle block. Thread-safe; the lock is a LEAF lock. The clock is
    monotonic (EDL406) — diary `ts` alone is wall-clock, for
    correlation."""

    def __init__(self, ring: int = RING_DEFAULT, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(ring)))  # guarded_by: _lock
        self._ops: Dict[str, _OpWindow] = {}                 # guarded_by: _lock
        self._attr: Dict[str, float] = {}                    # guarded_by: _lock
        self._slow_wall = 0.0                                # guarded_by: _lock
        self._finished = 0                                   # guarded_by: _lock
        self._by_status = {s: 0 for s in FINISH_STATUSES}    # guarded_by: _lock
        self._retained = 0                                   # guarded_by: _lock
        # previous payload() snapshot, for the windowed shares
        self._prev_payload: Optional[Dict[str, float]] = None  # guarded_by: _lock

    # ------------------------------------------------------------------ #
    # hot path

    def start(self, op: str, **meta) -> Diary:
        """Open a diary and push it onto the calling thread's stack.
        `op` values are call-site literals (pull / pull_multi / push /
        tier_pull / serve / …) — the label set stays bounded."""
        d = Diary(op, self._clock, meta or None)
        _stack().append(d)
        return d

    def finish(self, d: Optional[Diary], status: str = "ok",
               detail: str = "") -> bool:
        """Close the diary; True when it was retained. The drop path is
        O(1): a deque append, a cached-threshold compare, two counter
        bumps."""
        if d is None:
            return False
        s = getattr(_TLS, "stack", None)
        if s and d in s:
            s.remove(d)
        d.wall_s = max(0.0, self._clock() - d.t0)
        d.status = status if status in FINISH_STATUSES else "error"
        d.detail = detail[:512]
        recalc_op = None
        with self._lock:
            win = self._ops.get(d.op)
            if win is None:
                win = self._ops[d.op] = _OpWindow()
            win.walls.append(d.wall_s)
            win.count += 1
            if win.count >= win.next_recalc:
                win.threshold_s = max(
                    FLOOR_S, quantile_sorted(sorted(win.walls), 0.99))
                win.next_recalc = win.count + RECALC_EVERY
                recalc_op = (d.op, win.threshold_s)
            self._finished += 1
            self._by_status[d.status] += 1
            slow = (win.threshold_s is not None
                    and d.wall_s > win.threshold_s)
            retain = slow or d.status != "ok"
            if retain:
                rec = d.to_dict()
                self._ring.append(rec)
                self._retained += 1
                for s, v in rec["stages"].items():
                    self._attr[s] = self._attr.get(s, 0.0) + v
                self._slow_wall += rec["wall_s"]
        if recalc_op is not None:
            # outside the leaf lock: metric locks are leaves too, but
            # the ordering discipline stays trivial this way
            _THRESHOLD.set(recalc_op[1], op=recalc_op[0])
        if not retain:
            _DIARIES.inc(outcome="dropped")
            return False
        outcome = ("retained_" + d.status) if d.status != "ok" \
            else "retained_slow"
        _DIARIES.inc(outcome=outcome)
        with self._lock:
            attr = dict(self._attr)
            wall = self._slow_wall
        for s, v in attr.items():
            # keys come from Diary.to_dict over the bounded STAGES
            # vocabulary: edl-lint: disable=EDL405
            _ATTR.set(round(v, 6), stage=s)
        _SLOW_WALL.set(round(wall, 6))
        return True

    def abandon(self, d: Optional[Diary]) -> None:
        """Unbind without recording (a call that was never attempted)."""
        s = getattr(_TLS, "stack", None)
        if d is not None and s and d in s:
            s.remove(d)

    # ------------------------------------------------------------------ #
    # rollups

    def retained(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def threshold_s(self, op: str) -> Optional[float]:
        with self._lock:
            win = self._ops.get(op)
            return win.threshold_s if win else None

    def snapshot(self) -> Dict:
        """The full per-process attribution picture (tests + /debug)."""
        with self._lock:
            attr = {s: round(self._attr.get(s, 0.0), 6) for s in STAGES
                    if self._attr.get(s, 0.0) > 0}
            known = sum(v for s, v in attr.items() if s != "other")
            return {
                "finished": self._finished,
                "by_status": dict(self._by_status),
                "retained": self._retained,
                "ring_len": len(self._ring),
                "slow_wall_s": round(self._slow_wall, 6),
                "attribution": attr,
                "known_share": (round(known / self._slow_wall, 6)
                                if self._slow_wall > 0 else 0.0),
                "thresholds_s": {
                    op: round(w.threshold_s, 6)
                    for op, w in self._ops.items()
                    if w.threshold_s is not None
                },
            }

    def dominant_stage(self) -> Optional[str]:
        """The stage with the most cumulative retained seconds,
        preferring attributed stages over the `other` residual."""
        with self._lock:
            attr = dict(self._attr)
        if not attr:
            return None
        named = {s: v for s, v in attr.items() if s != "other"}
        pool = named or attr
        return max(sorted(pool), key=lambda s: pool[s])

    def payload(self) -> Dict[str, float]:
        """Compact heartbeat ride-along (bounded key count — the stats
        codec truncates past MAX_PAYLOAD_KEYS):

            rt_slow / rt_slow_wall_s   retained count + wall total
            rt_dom / rt_dom_share      dominant stage (STAGES index)
            rt_known_share             attributed (non-`other`) fraction
            emb_degraded_share         degraded finishes / finishes,
                                       windowed between payload calls
            emb_shm_fallback_share     shm fallbacks / shm attempts,
                                       windowed, from the shm counters
        """
        with self._lock:
            attr = dict(self._attr)
            wall = self._slow_wall
            retained = self._retained
            finished = self._finished
            degraded = self._by_status["degraded"]
        out: Dict[str, float] = {}
        if retained:
            out["rt_slow"] = float(retained)
            out["rt_slow_wall_s"] = round(wall, 3)
            named = {s: v for s, v in attr.items() if s != "other"}
            pool = named or attr
            dom = max(sorted(pool), key=lambda s: pool[s])
            out["rt_dom"] = float(STAGES.index(dom))
            if wall > 0:
                out["rt_dom_share"] = round(pool[dom] / wall, 4)
                out["rt_known_share"] = round(
                    sum(named.values()) / wall, 4)
        shm_calls, shm_fb = _shm_totals()
        cur = {
            "finished": float(finished), "degraded": float(degraded),
            "shm_calls": shm_calls, "shm_fb": shm_fb,
        }
        with self._lock:
            prev, self._prev_payload = self._prev_payload, cur
        if prev is not None:
            dfin = cur["finished"] - prev["finished"]
            ddeg = cur["degraded"] - prev["degraded"]
            if dfin > 0 and ddeg >= 0:
                out["emb_degraded_share"] = round(
                    min(1.0, ddeg / dfin), 4)
            dcalls = cur["shm_calls"] - prev["shm_calls"]
            dfb = cur["shm_fb"] - prev["shm_fb"]
            if dcalls + dfb > 0 and dfb >= 0 and dcalls >= 0:
                out["emb_shm_fallback_share"] = round(
                    min(1.0, dfb / (dcalls + dfb)), 4)
        return out

    def bundle_block(self) -> Optional[Dict]:
        """The flight-recorder `diaries` block: totals, the attribution
        decomposition, and the worst retained diaries (replay-identical
        to the ring's entries). None when nothing was ever recorded —
        absence must read as no-data, not as an empty tail."""
        with self._lock:
            if not self._finished:
                return None
            ring = list(self._ring)
            attr = {s: round(v, 6) for s, v in self._attr.items()}
            block = {
                "schema": 1,
                "finished": self._finished,
                "by_status": dict(self._by_status),
                "retained": self._retained,
                "dropped": self._finished - self._retained,
                "slow_wall_s": round(self._slow_wall, 6),
                "attribution": attr,
                "thresholds_s": {
                    op: round(w.threshold_s, 6)
                    for op, w in self._ops.items()
                    if w.threshold_s is not None
                },
            }
        worst = sorted(ring, key=lambda r: r["wall_s"], reverse=True)
        block["slow_calls"] = worst[:BUNDLE_SLOW_CALLS]
        return block


def _shm_totals():
    """(calls, fallbacks) totals from the shm counters, via the
    registry so this module never imports the embedding package."""
    calls = fb = 0.0
    m = _reg.get("edl_emb_shm_calls_total")
    if m is not None:
        try:
            calls = sum(m.snapshot().values())
        except Exception:
            # a broken metric must not break the heartbeat:
            # edl-lint: disable=EDL303
            calls = 0.0
    m = _reg.get("edl_emb_shm_fallbacks_total")
    if m is not None:
        try:
            fb = sum(m.snapshot().values())
        except Exception:
            # same contract: edl-lint: disable=EDL303
            fb = 0.0
    return calls, fb


# ---------------------------------------------------------------------- #
# fleet rollup (master side)


class FleetAttribution:
    """Stateful fleet view over heartbeat `rt_*` payloads: names the
    fleet-dominant slow stage and pulses `…_dom_shift` when it moves
    (wire -> budget_wait is the canonical partition signature) — the
    series the `emb_attr_dominant_shift` default alert rule watches.
    One instance lives on the master next to FleetGoodput; `series()`
    feeds the sampler extra. Absence of data emits nothing (no-data to
    the rules, never a zero)."""

    def __init__(self):
        self._prev_dom: Optional[int] = None

    def series(self, health_records: List[Dict],
               stale_after_s: float = 30.0,
               now: Optional[float] = None) -> Dict[str, float]:
        now = time.time() if now is None else now
        worst_wall = 0.0
        dom: Optional[int] = None
        known: Optional[float] = None
        for rec in health_records:
            try:
                updated = float(rec.get("updated_at") or 0.0)
            except (TypeError, ValueError):
                continue
            if now - updated > stale_after_s:
                continue
            wall = rec.get("rt_slow_wall_s")
            d = rec.get("rt_dom")
            if not isinstance(wall, (int, float)) or isinstance(wall, bool):
                continue
            if wall <= 0 or not isinstance(d, (int, float)):
                continue
            ks = rec.get("rt_known_share")
            if isinstance(ks, (int, float)) and not isinstance(ks, bool):
                known = ks if known is None else min(known, float(ks))
            # worst-reporter: the process with the largest retained slow
            # wall owns the fleet's tail story
            if wall >= worst_wall:
                worst_wall = float(wall)
                dom = int(d)
        if dom is None:
            return {}
        out = {"edl_fleet_emb_attr_dom_stage": float(dom)}
        shifted = self._prev_dom is not None and dom != self._prev_dom
        self._prev_dom = dom
        out["edl_fleet_emb_attr_dom_shift"] = 1.0 if shifted else 0.0
        if known is not None:
            out["edl_fleet_emb_attr_known_share"] = round(float(known), 4)
        return out


# ---------------------------------------------------------------------- #
# process singleton


_RECORDER: Optional[DiaryRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_recorder() -> DiaryRecorder:
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = DiaryRecorder()
        return _RECORDER


def reset_for_tests() -> None:
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = None
    _TLS.stack = []
