"""Shared decision-seam hook plumbing.

Both decision seams — `ClusterHealth.add_hook` (straggler onsets) and
`AlertEngine.add_hook` (alert onsets) — swallow consumer exceptions by
contract (scoring/evaluation must survive a crashing policy), but a
swallowed failure must never be DARK: it is counted on /metrics and
WARNING-logged with the hook's name, so a crashing autoscaler policy is
an incident visible in the flight ring, not a debug curiosity. One
helper so the two seams cannot drift (ISSUE 14 satellite + review
finding)."""

from __future__ import annotations

from elasticdl_tpu.observability.registry import default_registry

_HOOK_ERRORS = default_registry().counter(
    "edl_hook_errors_total",
    "decision-seam hook callbacks that raised (swallowed, but counted)",
    labels=("source",))


def observe_hook_failure(source: str, hook, logger) -> None:
    """Count + name one swallowed hook exception. Call from inside the
    `except` block (logs with exc_info). `source` values come from the
    bounded two-seam literal set at every call site:
    edl-lint: disable=EDL405"""
    _HOOK_ERRORS.inc(source=source)
    logger.warning(
        "%s hook %s failed (swallowed; counted in "
        "edl_hook_errors_total{source=%s})",
        source,
        getattr(hook, "__qualname__", None)
        or getattr(hook, "__name__", repr(hook)),
        source,
        exc_info=True,
    )
