"""Declarative alert rules over the time-series store.

The sensing stack can now say what happened (metrics), why (profiler),
and when (time series) — but every consequence is still a log line
someone has to be reading. This module closes the observe->decide gap
with a small, declarative rule engine the master evaluates on its
existing `ClusterHealth` poll:

- **`AlertRule`**: one named condition over ONE series in the store —
  `value` (latest sample), `avg`/`quantile` (window), `rate` (counter
  rate-of-change, reset-aware), or `burn_rate` (the SRE multi-window
  shape: the condition must hold over BOTH a short and a long window,
  so a transient spike doesn't page and a sustained burn does). `for_s`
  additionally requires the condition to hold continuously before the
  alert fires.
- **`AlertEngine`**: edge-triggered evaluation. One `cluster.alert`
  trace event + hook invocation at ONSET, one `cluster.alert_cleared`
  at recovery — never one per poll. An active alert whose series goes
  dark (no samples in the window: reporter died, fleet below quorum)
  is CARRIED FORWARD, not cleared — "we lost the ability to evaluate"
  must not read as "the problem went away" (the same contract as the
  straggler scorer's carried-forward flag). Page-severity onsets dump
  the process flight ring (riding PR 8's escalation machinery), so the
  black box is cut at the moment the condition tripped.
- **metrics**: `edl_alert_active{rule}` (1 while firing) and
  `edl_alert_transitions_total{rule}` (onsets + clears).
- **hooks**: `add_hook(cb)` — cb(alert_info) fires once per onset; this
  is the pluggable seam ROADMAP 3's autoscaler subscribes to, exactly
  like `ClusterHealth.add_hook` for stragglers. Hook exceptions are
  swallowed: evaluation must survive its consumers.
- **`/alerts`** (observability/http.py) serves `snapshot()`; with a
  json_path configured, every transition (and `write_json()`) lands an
  atomic `alerts.json` next to the job's other artifacts.

Shipped default rules (docs/observability.md "Alert rules") cover the
sensor set ROADMAP 3's autoscaler needs: straggler presence, dispatcher
backlog per worker, a data_wait-dominant fleet (more workers will not
help an input-bound job), embedding pull p99, and embedding shard load
imbalance. Rules can also be loaded from a JSON file (`--alert_rules`).

Stdlib-only, jax-free, and strictly best-effort: `evaluate()` never
raises.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry
from elasticdl_tpu.observability.timeseries import TimeSeriesStore

logger = default_logger(__name__)

_reg = default_registry()
_AL_ACTIVE = _reg.gauge(
    "edl_alert_active", "1 while the rule's condition is firing",
    labels=("rule",))
_AL_TRANSITIONS = _reg.counter(
    "edl_alert_transitions_total",
    "alert state transitions (onsets + clears)", labels=("rule",))

#: evaluation modes an AlertRule may use
MODES = ("value", "avg", "quantile", "rate", "burn_rate")
SEVERITIES = ("warn", "page")

#: recent transitions kept for /alerts and alerts.json
HISTORY_KEEP = 128


@dataclass
class AlertRule:
    """One declarative condition over one time-series."""

    name: str
    series: str
    threshold: float
    op: str = ">"              # ">" or "<"
    mode: str = "value"        # see MODES
    window_s: float = 60.0
    long_window_s: float = 0.0  # burn_rate: the confirming long window
    quantile: float = 0.99     # quantile mode only
    for_s: float = 0.0         # condition must hold this long pre-onset
    severity: str = "warn"     # "warn" | "page" (page dumps the ring)
    description: str = ""

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(
                f"alert rule {self.name!r}: mode {self.mode!r} not in "
                f"{MODES}")
        if self.op not in (">", "<"):
            raise ValueError(
                f"alert rule {self.name!r}: op must be '>' or '<'")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"alert rule {self.name!r}: severity {self.severity!r} "
                f"not in {SEVERITIES}")
        if self.mode == "burn_rate" and self.long_window_s <= self.window_s:
            raise ValueError(
                f"alert rule {self.name!r}: burn_rate needs "
                "long_window_s > window_s")

    # -------------------------------------------------------------- #

    def _measure(self, store: TimeSeriesStore, window_s: float,
                 now: float) -> Optional[float]:
        if self.mode == "value":
            return store.latest(self.series, now=now, max_age_s=window_s)
        if self.mode == "avg" or self.mode == "burn_rate":
            return store.avg(self.series, window_s, now=now)
        if self.mode == "quantile":
            return store.quantile(
                self.series, self.quantile, window_s, now=now)
        if self.mode == "rate":
            return store.rate(self.series, window_s, now=now)
        return None

    def _breaches(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" \
            else value < self.threshold

    def evaluate(self, store: TimeSeriesStore,
                 now: float) -> "Optional[Dict]":
        """None = no data (the engine carries active alerts forward);
        else {"bad": bool, "value": measured} — for burn_rate, bad means
        BOTH windows breach and `value` is the short window's."""
        short = self._measure(store, self.window_s, now)
        if short is None:
            return None
        bad = self._breaches(short)
        out = {"bad": bad, "value": short}
        if self.mode == "burn_rate" and bad:
            long_v = self._measure(store, self.long_window_s, now)
            if long_v is None:
                return None
            out["long_value"] = long_v
            out["bad"] = self._breaches(long_v)
        return out


def default_rules() -> List[AlertRule]:
    """The shipped sensor set — every series here is produced by the
    master's fleet sampler (timeseries.fleet_series). Thresholds are
    deliberately conservative defaults; jobs tune via --alert_rules."""
    return [
        AlertRule(
            "straggler", series="edl_fleet_straggler_count",
            threshold=0.5, mode="value", window_s=60.0, severity="warn",
            description="ClusterHealth scored >=1 worker as a straggler",
        ),
        AlertRule(
            "dispatcher_backlog_per_worker",
            series="edl_fleet_backlog_per_worker",
            threshold=64.0, mode="avg", window_s=60.0, for_s=30.0,
            severity="warn",
            description="todo tasks per alive worker high and sustained "
                        "— the grow signal for ROADMAP 3's autoscaler",
        ),
        AlertRule(
            "fleet_data_wait_dominant",
            series="edl_fleet_data_wait_frac",
            threshold=0.5, mode="burn_rate", window_s=60.0,
            long_window_s=300.0, severity="warn",
            description="the fleet spends most of its step time blocked "
                        "on input — more workers will not help",
        ),
        AlertRule(
            "embedding_pull_p99",
            series="edl_fleet_emb_pull_p99_ms",
            threshold=250.0, mode="burn_rate", window_s=30.0,
            long_window_s=120.0, severity="page",
            description="embedding tier pull p99 sustained past budget "
                        "— pulls are on the step critical path",
        ),
        AlertRule(
            "embedding_shard_imbalance",
            series="edl_fleet_emb_shard_imbalance",
            threshold=3.0, mode="avg", window_s=30.0, for_s=10.0,
            severity="page",
            description="one embedding shard serves >3x the mean load — "
                        "the hot-row-cache / replica signal (ROADMAP 1)",
        ),
        # ISSUE 13 (embedding read path): the fleet series is the WORST
        # (minimum) reporter's recent-window hit rate, present only when
        # a cache is actually running — no cache, no data, no page. A
        # sustained collapse means the hot set migrated out from under
        # the cache (campaign launch, day/night id shift): re-seed from
        # the sketch / grow --embedding_cache_rows before owner RPC load
        # multiplies by 1/(1-hit_rate).
        AlertRule(
            "embedding_cache_hit_collapse",
            series="edl_fleet_emb_cache_hit_rate",
            threshold=0.2, op="<", mode="avg", window_s=60.0,
            for_s=30.0, severity="warn",
            description="hot-row cache hit rate collapsed on at least "
                        "one worker — hot-set migration; owner shards "
                        "are about to absorb the uncached read load",
        ),
        # ISSUE 12 (observability/goodput.py): the two rules that watch
        # the bill itself. Both series come from the master's
        # FleetGoodput rollup riding the fleet sampler.
        # both goodput rules watch the WINDOWED (per-rollup-delta)
        # series, not the lifetime-cumulative ones: after 10h at 0.9 a
        # 30-minute stall barely moves a cumulative fraction, and a long
        # boot compile would depress it past any for_s hold — the recent
        # series measure the last interval and reach the store ONLY via
        # FleetGoodput.series() (deliberately no registry gauge — see
        # observability/goodput.py's note above its gauge factories)
        AlertRule(
            "goodput_burn",
            series="edl_goodput_fleet_recent_fraction",
            threshold=0.5, op="<", mode="burn_rate", window_s=60.0,
            long_window_s=300.0, for_s=120.0, severity="warn",
            description="fleet goodput fraction (windowed) sustained "
                        "below half — most paid chip-seconds are not "
                        "training; read /goodput for the category "
                        "breakdown (for_s rides out boot compiles)",
        ),
        AlertRule(
            "wasted_work_ratio",
            series="edl_goodput_recent_wasted_ratio",
            threshold=0.05, mode="avg", window_s=120.0, for_s=30.0,
            severity="warn",
            description="over 5% of recently-processed training records "
                        "are being re-trained (requeues after "
                        "crash/expiry) — crash-replay or lease-timeout "
                        "churn",
        ),
        # ISSUE 19 (observability/reqtrace.py): the fleet's dominant
        # slow-request stage MOVED (e.g. wire -> budget_wait, the
        # partition signature). FleetAttribution emits a 1.0 pulse on
        # the sample where the worst reporter's dominant stage differs
        # from the previous rollup, 0.0 otherwise — a plain value rule
        # turns that into an edge-triggered alert that clears on the
        # next steady sample. The absolute p99 level already has
        # embedding_pull_p99; this rule fires on the SHAPE changing.
        AlertRule(
            "emb_attr_dominant_shift",
            series="edl_fleet_emb_attr_dom_shift",
            threshold=0.5, mode="value", window_s=60.0,
            severity="warn",
            description="the dominant per-stage attribution of slow "
                        "embedding reads shifted (see "
                        "edl_fleet_emb_attr_dom_stage and the incident "
                        "CLI's slow_calls waterfalls for where the p99 "
                        "moved)",
        ),
    ]


def rules_from_json(data) -> List[AlertRule]:
    """Parse a rules document: a JSON list of AlertRule field dicts.
    Unknown keys are rejected (a typo'd threshold key silently keeping a
    default is exactly the failure mode declarative rules exist to
    avoid)."""
    if not isinstance(data, list):
        raise ValueError("alert rules document must be a JSON list")
    allowed = set(AlertRule.__dataclass_fields__)
    rules = []
    for i, entry in enumerate(data):
        if not isinstance(entry, dict):
            raise ValueError(f"alert rule #{i} is not an object")
        unknown = set(entry) - allowed
        if unknown:
            raise ValueError(
                f"alert rule #{i} has unknown keys {sorted(unknown)}")
        rules.append(AlertRule(**entry))
    return rules


def rules_from_config(cfg) -> Optional[List[AlertRule]]:
    """--alert_rules resolution: "" = defaults, "off" = no rules (engine
    disabled), a path = defaults REPLACED by the file's rules. A bad
    file fails at boot — a silently-defaulted alert config is worse than
    a loud one."""
    raw = (getattr(cfg, "alert_rules", "") or "").strip()
    if not raw:
        return default_rules()
    if raw.lower() == "off":
        return []
    with open(raw, encoding="utf-8") as f:
        return rules_from_json(json.load(f))


class AlertEngine:
    """Edge-triggered evaluation of AlertRules against a store."""

    def __init__(self, store: TimeSeriesStore,
                 rules: Optional[List[AlertRule]] = None,
                 json_path: Optional[str] = None,
                 flight_dump: Optional[Callable[[str], None]] = None):
        self._store = store
        self.rules = list(rules) if rules is not None else default_rules()
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names in {names}")
        self.json_path = json_path or None
        self._hooks: List[Callable[[Dict], None]] = []
        self._lock = threading.Lock()
        self._active: Dict[str, Dict] = {}        # guarded_by: _lock
        self._pending_since: Dict[str, float] = {}  # guarded_by: _lock
        self._history: "deque[Dict]" = deque(maxlen=HISTORY_KEEP)  # guarded_by: _lock
        self._evaluations = 0                      # guarded_by: _lock
        # page-severity onset cuts the black box; injectable for tests
        if flight_dump is None:
            def flight_dump(reason: str) -> None:
                from elasticdl_tpu.observability import flight as flight_lib

                flight_lib.get_recorder().dump(reason)
        self._flight_dump = flight_dump

    def add_hook(self, cb: Callable[[Dict], None]) -> None:
        """cb(alert_info) fires once per alert ONSET — the autoscaler
        seam (ROADMAP 3), mirroring ClusterHealth.add_hook."""
        self._hooks.append(cb)

    # ------------------------------------------------------------------ #

    def evaluate(self, now: Optional[float] = None) -> Dict:
        """One evaluation pass; returns the state snapshot. Never raises
        (the master's wait loop calls this unconditionally)."""
        try:
            return self._evaluate(now)
        except Exception:
            logger.exception("alert evaluation failed; keeping last state")
            return self.snapshot()

    def _evaluate(self, now: Optional[float] = None) -> Dict:
        now = time.time() if now is None else now
        onsets: List[Dict] = []
        cleared: List[Dict] = []
        with self._lock:
            self._evaluations += 1
            for rule in self.rules:
                result = rule.evaluate(self._store, now)
                active = self._active.get(rule.name)
                if result is None:
                    # no data: carry an active alert forward (clearing on
                    # blindness would close the incident spuriously and
                    # double-count the onset when data returns), drop any
                    # pending timer (we cannot know the condition held)
                    self._pending_since.pop(rule.name, None)
                    if active is not None:
                        active["carried_forward"] = True
                    continue
                if result["bad"]:
                    since = self._pending_since.setdefault(rule.name, now)
                    if active is not None:
                        active["value"] = result["value"]
                        active["carried_forward"] = False
                        continue
                    if now - since < rule.for_s:
                        continue   # pending, not yet held long enough
                    info = {
                        "rule": rule.name,
                        "severity": rule.severity,
                        "series": rule.series,
                        "mode": rule.mode,
                        "op": rule.op,
                        "threshold": rule.threshold,
                        "value": round(float(result["value"]), 6),
                        "since": round(since, 3),
                        "ts": round(now, 3),
                        "description": rule.description,
                        "carried_forward": False,
                    }
                    if "long_value" in result:
                        info["long_value"] = round(
                            float(result["long_value"]), 6)
                    self._active[rule.name] = info
                    onsets.append(dict(info))
                else:
                    self._pending_since.pop(rule.name, None)
                    if active is not None:
                        del self._active[rule.name]
                        cleared.append(dict(
                            active, cleared_ts=round(now, 3)))
            for info in onsets:
                self._history.append(dict(info, transition="firing"))
            for info in cleared:
                self._history.append(dict(info, transition="cleared"))

        # metrics + events + hooks OUTSIDE the lock (trace emission is
        # file I/O — EDL402's idiom)
        for info in onsets:
            # rule-name labels are bounded by the declared rule set (a
            # handful, validated unique at construction), not by data:
            # edl-lint: disable=EDL405
            _AL_ACTIVE.set(1, rule=info["rule"])
            # edl-lint: disable=EDL405
            _AL_TRANSITIONS.inc(rule=info["rule"])
            tracing.event(
                "cluster.alert", rule=info["rule"],
                severity=info["severity"], series=info["series"],
                value=info["value"], threshold=info["threshold"],
            )
            logger.warning(
                "ALERT %s [%s]: %s %s %s %s (value %.6g)",
                info["rule"], info["severity"], info["series"],
                info["mode"], info["op"], info["threshold"], info["value"],
            )
            if info["severity"] == "page":
                # the black box, cut at the moment the page tripped —
                # dump() never raises
                self._flight_dump(f"alert:{info['rule']}")
            for hook in self._hooks:
                try:
                    hook(dict(info))
                except Exception:
                    # swallowed (evaluation must survive its consumers)
                    # but never dark: counted + named (observability/
                    # hooks.py — shared with ClusterHealth's seam)
                    from elasticdl_tpu.observability.hooks import (
                        observe_hook_failure,
                    )

                    observe_hook_failure("alert_engine", hook, logger)
        for info in cleared:
            # bounded by the declared rule set (see the onset loop):
            # edl-lint: disable=EDL405
            _AL_ACTIVE.set(0, rule=info["rule"])
            # edl-lint: disable=EDL405
            _AL_TRANSITIONS.inc(rule=info["rule"])
            tracing.event("cluster.alert_cleared", rule=info["rule"])
            logger.info("alert cleared: %s", info["rule"])
        if (onsets or cleared) and self.json_path:
            self.write_json()
        return self.snapshot()

    # ------------------------------------------------------------------ #

    def snapshot(self) -> Dict:
        """The /alerts payload: active alerts + recent transitions + the
        rule table (cheap; never recomputes)."""
        with self._lock:
            return {
                "ts": time.time(),
                "evaluations": self._evaluations,
                "active": sorted(
                    (dict(i) for i in self._active.values()),
                    key=lambda i: i["rule"]),
                "history": list(self._history),
                "rules": [asdict(r) for r in self.rules],
            }

    def active(self) -> List[Dict]:
        with self._lock:
            return sorted(
                (dict(i) for i in self._active.values()),
                key=lambda i: i["rule"])

    def write_json(self, path: Optional[str] = None) -> Optional[str]:
        """Persist the snapshot atomically (tmp + os.replace — EDL305) as
        alerts.json; never raises."""
        target = path or self.json_path
        if not target:
            return None
        try:
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            tmp = target + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.snapshot(), f, indent=1, sort_keys=True,
                          default=repr)
                f.write("\n")
            os.replace(tmp, target)
        except Exception:
            logger.exception("alerts.json write to %s failed", target)
            return None
        return target


# kept importable for tests asserting the field set stays declarative
RULE_FIELDS = tuple(AlertRule.__dataclass_fields__)
