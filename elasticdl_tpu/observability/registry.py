"""Process-local metrics registry: counters, gauges, histograms.

Design constraints:

- stdlib-only, jax-free: this is imported by modules on the client submit
  path and by the analysis package; it must cost nothing but a dict and a
  lock.
- thread-safe: every metric mutation is under the metric's own lock, and
  the registry's create-or-get is under the registry lock. Metric locks
  are LEAF locks — nothing inside them acquires any other lock — so
  incrementing a counter while holding a subsystem lock (dispatcher,
  membership) can never participate in a lock-order cycle.
- bounded memory: histograms keep a fixed-size reservoir (uniform
  reservoir sampling), so an unbounded stream of observations costs O(1).
- idempotent registration: `registry.counter(name, ...)` returns the
  existing metric when `name` is already registered (modules declare
  their metrics at import time; re-imports and multiple instances share
  one series). Re-registering under a different KIND is a hard error.
- naming: every name must match `edl_<subsystem>_<name>`
  (`_NAME_RE`) — enforced here at runtime and by edl-lint EDL401
  statically, so the scrape surface stays grep-able and collision-free.
"""

from __future__ import annotations

import random
import re
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

#: the project metric naming pattern (edl-lint EDL401 mirrors this)
_NAME_RE = re.compile(r"^edl_[a-z][a-z0-9]*_[a-z0-9_]*[a-z0-9]$")

#: default histogram reservoir size — big enough for stable p99 on
#: control-plane event rates, small enough to never matter in RAM
DEFAULT_RESERVOIR = 512

_QUANTILES = (0.5, 0.9, 0.99)


def validate_metric_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} does not match the project pattern "
            "edl_<subsystem>_<name> (lowercase, underscore-separated; "
            "see docs/observability.md and edl-lint EDL401)"
        )
    return name


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _render_labels(names: Tuple[str, ...], values: Tuple[str, ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(names, values)) + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + body + "}"


def quantile_sorted(sorted_vals, q: float) -> float:
    """Linear-interpolation quantile over an ASCENDING-sorted sequence
    (0.0 when empty) — the one implementation `_Reservoir` and the worker
    health stats share (observability/health.py)."""
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def _fmt(value: float) -> str:
    # integers print as integers (Prometheus accepts both; humans diff this)
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class Metric:
    """Base: one named series family (labelled children share the name)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = ()):
        self.name = validate_metric_name(name)
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(labels)
        self._lock = threading.Lock()

    def _label_key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[k]) for k in self.label_names)

    def render(self) -> List[str]:
        raise NotImplementedError

    def snapshot(self) -> Dict[str, float]:
        raise NotImplementedError

    def _series_name(self, key: Tuple[str, ...]) -> str:
        return self.name + _render_labels(self.label_names, key)


class Counter(Metric):
    """Monotonic counter; `inc(n, **labels)`."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = ()):
        super().__init__(name, help, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (n={n})")
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        key = self._label_key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            items = sorted(self._values.items())
        if not self.label_names and not items:
            items = [((), 0.0)]
        return {self._series_name(k): v for k, v in items}

    def render(self) -> List[str]:
        return [f"{n} {_fmt(v)}" for n, v in self.snapshot().items()]


class Gauge(Metric):
    """Point-in-time value: `set()`/`add()`, or a `set_fn` callback read at
    scrape/snapshot time (for values another subsystem already owns, e.g.
    the compile cache's hit rate — no double bookkeeping)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = ()):
        super().__init__(name, help, labels)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float, **labels: str) -> None:
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = float(value)

    def add(self, n: float = 1.0, **labels: str) -> None:
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def set_fn(self, fn: Callable[[], float]) -> "Gauge":
        """Compute the (unlabelled) value at read time. The callback runs
        OUTSIDE the metric lock and must not raise for long — a failing
        callback reads as 0 rather than breaking the whole scrape."""
        if self.label_names:
            raise ValueError(f"{self.name}: set_fn is unlabelled-only")
        self._fn = fn
        return self

    def value(self, **labels: str) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                # a failing callback reads as 0 — the scrape (and with it
                # the hot path behind it) must never inherit a subsystem's
                # exception: edl-lint: disable=EDL303
                return 0.0
        key = self._label_key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def snapshot(self) -> Dict[str, float]:
        if self._fn is not None:
            return {self.name: self.value()}
        with self._lock:
            items = sorted(self._values.items())
        if not self.label_names and not items:
            items = [((), 0.0)]
        return {self._series_name(k): v for k, v in items}

    def render(self) -> List[str]:
        return [f"{n} {_fmt(v)}" for n, v in self.snapshot().items()]


class _Reservoir:
    """Uniform (Vitter algorithm R) bounded sample + exact count/sum/max."""

    __slots__ = ("sample", "count", "sum", "max", "capacity", "_rng")

    def __init__(self, capacity: int, rng: random.Random):
        self.sample: List[float] = []
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self.capacity = capacity
        self._rng = rng

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if self.count == 1 or v > self.max:
            self.max = v
        if len(self.sample) < self.capacity:
            self.sample.append(v)
        else:
            i = self._rng.randrange(self.count)
            if i < self.capacity:
                self.sample[i] = v

    def quantile(self, q: float) -> float:
        return quantile_sorted(sorted(self.sample), q)


class Histogram(Metric):
    """Sampled distribution rendered as a Prometheus SUMMARY (quantile
    series + _sum/_count). The reservoir bounds memory; quantiles are
    estimates over the sample, exact until `count > reservoir`."""

    kind = "summary"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 reservoir: int = DEFAULT_RESERVOIR):
        super().__init__(name, help, labels)
        self._reservoir_size = max(1, int(reservoir))
        # seeded per metric name: deterministic sampling for tests, and no
        # dependence on global random state
        self._rng = random.Random(name)
        self._children: Dict[Tuple[str, ...], _Reservoir] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = self._label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _Reservoir(self._reservoir_size, self._rng)
                self._children[key] = child
            child.observe(float(value))

    def count(self, **labels: str) -> int:
        key = self._label_key(labels)
        with self._lock:
            child = self._children.get(key)
            return child.count if child else 0

    def quantile(self, q: float, **labels: str) -> float:
        key = self._label_key(labels)
        with self._lock:
            child = self._children.get(key)
            return child.quantile(q) if child else 0.0

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        with self._lock:
            items = sorted(self._children.items())
            for key, child in items:
                suffix = _render_labels(self.label_names, key)
                out[self.name + "_count" + suffix] = float(child.count)
                out[self.name + "_sum" + suffix] = child.sum
                # sort the reservoir ONCE for all quantiles — snapshot
                # runs on the summary-stream/time-series cadence, and
                # per-quantile sorts triple its dominant cost
                s = sorted(child.sample)
                for q in _QUANTILES:
                    out[f"{self.name}_p{int(q * 100)}{suffix}"] = (
                        quantile_sorted(s, q)
                    )
        return out

    def render(self) -> List[str]:
        lines: List[str] = []
        with self._lock:
            items = sorted(self._children.items())
            for key, child in items:
                for q in _QUANTILES:
                    labels = _render_labels(
                        self.label_names, key, (("quantile", str(q)),)
                    )
                    lines.append(
                        f"{self.name}{labels} {_fmt(child.quantile(q))}"
                    )
                suffix = _render_labels(self.label_names, key)
                lines.append(f"{self.name}_sum{suffix} {_fmt(child.sum)}")
                lines.append(
                    f"{self.name}_count{suffix} {_fmt(child.count)}"
                )
        return lines


class MetricsRegistry:
    """Create-or-get metric store; renders Prometheus text format."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}
        self.created_at = time.time()

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Iterable[str], **kw) -> Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}"
                    )
                return existing
            metric = cls(name, help, labels, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  reservoir: int = DEFAULT_RESERVOIR) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, reservoir=reservoir
        )

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> Dict[str, float]:
        """Flat {series_name: value} — the summary-service stream and the
        bench both consume this. Callback gauges are evaluated here."""
        out: Dict[str, float] = {}
        for metric in self.metrics():
            try:
                out.update(metric.snapshot())
            except Exception:
                # one broken metric must not take the whole snapshot down:
                # edl-lint: disable=EDL303
                continue
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        lines: List[str] = []
        for metric in self.metrics():
            if metric.help:
                lines.append(f"# HELP {metric.name} {metric.help}")
            lines.append(f"# TYPE {metric.name} {metric.kind}")
            try:
                lines.extend(metric.render())
            except Exception:
                # scrape keeps serving the healthy series:
                # edl-lint: disable=EDL303
                continue
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------- #
# the process-global default registry every wired subsystem shares

_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
