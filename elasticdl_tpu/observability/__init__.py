"""Elastic control-plane observability: metrics registry + trace spans +
scrape surface.

Three stdlib-only layers (nothing here may import jax — the registry and
tracer are wired into modules that must stay importable everywhere,
including the framework-free client submit path):

- `registry`: process-local metrics (counters, gauges, histograms with
  bounded reservoirs; thread-safe), rendered in Prometheus text format.
  Every metric name follows `edl_<subsystem>_<name>` — enforced at
  registration time AND statically by edl-lint EDL401.
- `tracing`: named spans/events for elastic lifecycle transitions
  (reform, rescale, checkpoint save/restore/handoff, speculative compile,
  RPC retries, prefetcher drains, task lease transitions), written as
  `trace.jsonl` lines carrying role, world version, and a trace id that
  propagates master<->worker through gRPC metadata so one resize produces
  one coherent cross-role timeline.
- `http`: a tiny stdlib HTTP endpoint (`/metrics` Prometheus text,
  `/healthz` JSON) the master and each worker expose, bound via
  `net.bind_with_retry`, strictly best-effort (fault site
  `metrics_scrape` lets chaos tests kill it and assert training never
  notices).
- `health`: the interpretation layer — heartbeat-piggybacked worker
  stats (gRPC metadata, optional/back-compatible) feeding per-worker
  rolling records in Membership, scored by a median/MAD straggler
  detector whose rollup rides the master's /metrics + /healthz.
- `analyzer` (+ the `analyze` CLI): offline trace merge and per-resize
  critical-path attribution over the `trace.jsonl` files.
- `flight`: the per-process incident black box — a bounded in-memory
  ring of recent spans/events/logs/metric deltas at full fidelity,
  dumped as an atomic `flight-<role>-<pid>.json` bundle on crash,
  SIGUSR2, `/debug/flight`, or straggler-hook escalation.
- `profile`: the always-on step profiler — per-step phase attribution
  (data_wait / h2d / compute / handoff) and memory watermarks, exported
  as `edl_step_phase_seconds` / `edl_mem_*` gauges and riding the
  heartbeat stats payload.
- `incident` (+ CLI): offline cross-role correlation of flight bundles,
  traces, the journal tail, and health snapshots into one timeline.

See docs/observability.md for the metric catalog and trace schema.
"""

from elasticdl_tpu.observability.registry import (  # noqa: F401
    MetricsRegistry,
    default_registry,
)
from elasticdl_tpu.observability import tracing  # noqa: F401
