"""CLI for the trace critical-path analyzer (observability/analyzer.py).

    python -m elasticdl_tpu.observability.analyze <path> [path ...]
        [--json] [--strict] [--trace-id ID] [--all-traces]

Paths are trace.jsonl files or directories (walked for ``*.jsonl`` — the
layout `--trace_dir` produces, one subdirectory per role, merges with no
flags). Text output shows each resize timeline's critical path and
per-phase/per-role attribution; ``--json`` emits the full report for
machines (CI stores it next to the trace artifacts).

Exit codes: 0 ok; 1 ``--strict`` violation (an unparseable line that is
not a file's torn tail — a writer bug, not a crash artifact); 2 usage —
no input files, or a named file that could not be opened at all (the
writer never ran; distinct from corruption).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from elasticdl_tpu.observability import analyzer


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.observability.analyze",
        description="merge trace.jsonl files and compute per-resize "
                    "critical paths",
    )
    parser.add_argument(
        "paths", nargs="+",
        help="trace.jsonl files and/or directories to walk for *.jsonl",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the full JSON report instead of text",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="exit 1 on unparseable NON-tail lines (torn final lines from "
             "a killed writer stay tolerated)",
    )
    parser.add_argument(
        "--trace-id", default=None,
        help="analyze only this trace id",
    )
    parser.add_argument(
        "--all-traces", action="store_true",
        help="text mode: show every trace, not just resize timelines",
    )
    args = parser.parse_args(argv)

    report = analyzer.analyze_paths(args.paths, trace_id=args.trace_id)
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(
            analyzer.render_text(report, resize_only=not args.all_traces),
            end="",
        )

    if not report["records"] and not report["files"]:
        print("no input files found", file=sys.stderr)
        return 2
    if report["unreadable_files"]:
        # a named-but-missing/unopenable file is a USAGE problem (the
        # writer never ran, the path is wrong) — exit 2, not a --strict
        # "writer bug" exit 1 (review find: a skipped best-effort trace
        # write must not be diagnosed as trace corruption)
        for path in report["unreadable_files"]:
            print(f"unreadable input file: {path}", file=sys.stderr)
        return 2
    if args.strict and report["strict_violations"]:
        for v in report["strict_violations"]:
            print(
                f"strict: unparseable line {v['file']}:{v['line']}: "
                f"{v['text']}", file=sys.stderr,
            )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
