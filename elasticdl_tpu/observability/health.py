"""Cluster health intelligence: heartbeat-piggybacked worker telemetry and
the master's robust straggler scorer.

PR 4 gave every process eyes (registry, spans, /metrics); nothing
*interpreted* that telemetry — stragglers were invisible until they missed
heartbeats entirely and got reaped. This module closes the loop:

- **Worker side** (`WorkerStepStats`, `encode_stats`): each worker keeps a
  bounded window of recent step times/records and piggybacks a compact
  JSON stats payload onto its existing Heartbeat RPC as gRPC metadata
  (`edl-worker-stats`). Metadata, not a proto field, because this image
  cannot regenerate message bindings (no protoc — the same constraint that
  shaped the membership signal file and the generation handshake), and
  metadata is exactly as optional as the payload must be: an old worker
  heartbeating a new master simply sends none and degrades to
  liveness-only; a new worker heartbeating an old master is ignored.
- **Master side** (`ClusterHealth` over `Membership`'s rolling per-worker
  health records): a median/MAD robust scorer over the fleet's step-time
  p50s. Median/MAD instead of mean/stddev because the statistic must not
  be dragged by the very outlier it is hunting — one 10x straggler shifts
  a mean-based z-score enough to hide itself. Scores feed cluster rollup
  gauges (`edl_cluster_*`, served by the master's /metrics), edge-triggered
  `cluster.straggler` trace events, and a pluggable hook — the seam the
  closed-loop autoscaler (master/autoscaler.py, ISSUE 14) subscribes to
  for drain-first straggler eviction; log-only when the autoscaler is
  off.

Everything here is stdlib-only and jax-free, like the rest of the
observability package, and strictly best-effort: a malformed payload, a
scorer hiccup, or a dead scrape endpoint must never touch liveness
handling or training. See docs/observability.md ("Cluster health").
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import (
    default_registry,
    quantile_sorted,
)

logger = default_logger(__name__)

#: gRPC metadata key the worker stats payload rides on (lowercase per
#: gRPC spec; absent = liveness-only heartbeat, the back-compat shape)
STATS_METADATA_KEY = "edl-worker-stats"

#: decode() rejects payloads past this — a corrupt/hostile value must cost
#: a bounded parse attempt, never master memory (key budget raised for
#: ISSUE 11's embedding skew ride-along — emb_* keys below — again for
#: ISSUE 12's goodput-ledger ride-along: up to 9 gp_* keys per worker,
#: observability/goodput.py payload schema, and again for ISSUE 19's
#: request-diary rollup: up to 7 rt_*/share keys per worker,
#: observability/reqtrace.py payload schema)
MAX_PAYLOAD_BYTES = 3584
MAX_PAYLOAD_KEYS = 56

#: step-profiler keys (observability/profile.py snapshot schema) plus the
#: embedding-tier skew keys (embedding/tier.tier_stats) carried from a
#: worker's health record into its straggler info — the WHY behind a
#: straggler flag ("blocked on input" / "melting under tier pulls")
_PROFILE_KEYS = (
    "phase_data_wait_ms", "phase_h2d_ms", "phase_compute_ms",
    "phase_handoff_ms", "mem_host_mb", "mem_dev_mb",
    "emb_pull_p99_ms", "emb_hot_id_share", "emb_shard_imbalance",
    # ISSUE 13 read path: effective (cache-included) read p99, recent
    # cache hit rate (the hot-set-migration sensor), pipeline lookahead
    "emb_read_p99_ms", "emb_cache_hit_rate", "emb_pipeline_depth",
    # ISSUE 19 tail attribution: the request-diary recorder's compact
    # rollup (observability/reqtrace.py payload schema) plus the
    # degraded/shm-fallback shares the fleet series aggregates
    "rt_slow", "rt_slow_wall_s", "rt_dom", "rt_dom_share",
    "rt_known_share", "emb_degraded_share", "emb_shm_fallback_share",
)

# cluster rollup gauges (master-side; docs/observability.md)
_reg = default_registry()
_CL_REPORTING = _reg.gauge(
    "edl_cluster_workers_reporting",
    "alive workers with fresh step telemetry this rollup")
_CL_SKEW = _reg.gauge(
    "edl_cluster_step_time_skew",
    "slowest/median step-time-p50 ratio (1.0 = uniform fleet)")
_CL_STRAGGLERS = _reg.gauge(
    "edl_cluster_straggler_count", "workers currently scored as stragglers")
_CL_SLOWEST = _reg.gauge(
    "edl_cluster_slowest_worker",
    "worker id with the highest step-time p50 (-1 = no data)")
_CL_FASTEST = _reg.gauge(
    "edl_cluster_fastest_worker",
    "worker id with the lowest step-time p50 (-1 = no data)")
_CL_MEDIAN = _reg.gauge(
    "edl_cluster_step_time_median_seconds",
    "fleet median of per-worker step-time p50s")
_CL_EVENTS = _reg.counter(
    "edl_cluster_straggler_events_total",
    "straggler onset detections (edge-triggered)")


# ---------------------------------------------------------------------- #
# payload codec (both sides import these; the schema lives here)


def encode_stats(stats: Dict) -> str:
    """Compact, ASCII-safe JSON for a gRPC metadata value."""
    return json.dumps(stats, separators=(",", ":"), sort_keys=True)


def decode_stats(raw: Optional[str]) -> Optional[Dict]:
    """Parse a heartbeat stats payload; None for anything that is not a
    well-formed, size-bounded JSON object. NEVER raises — a worker from a
    different build (mid-rolling-restart) sending tomorrow's schema, or
    garbage, degrades that heartbeat to liveness-only."""
    if not raw or not isinstance(raw, str) or len(raw) > MAX_PAYLOAD_BYTES:
        return None
    try:
        data = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(data, dict) or len(data) > MAX_PAYLOAD_KEYS:
        return None
    out: Dict = {}
    for k, v in data.items():
        if not isinstance(k, str):
            return None
        # scalars only — the record is a flat metrics row, and bounding
        # the value shapes here bounds master memory per worker forever
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = v
        elif isinstance(v, str):
            out[k] = v[:64]
        # anything else (nested containers, null) is dropped, not fatal
    return out


# ---------------------------------------------------------------------- #
# worker side


class WorkerStepStats:
    """Bounded window of recent step timings, summarized into the
    heartbeat payload. Thread-safe: the train loop observes, the heartbeat
    thread snapshots. The lock is a LEAF lock (nothing inside it acquires
    anything else), so observing from the hot loop cannot deadlock."""

    def __init__(self, window: int = 128):
        self._lock = threading.Lock()
        self._steps: "deque[float]" = deque(maxlen=window)   # guarded_by: _lock
        self._records: "deque[float]" = deque(maxlen=window)  # guarded_by: _lock

    def observe_step(self, seconds: float, records: float = 0.0) -> None:
        with self._lock:
            self._steps.append(float(seconds))
            self._records.append(float(records))

    def snapshot(self) -> Dict:
        """The timing half of the heartbeat payload (ms keep the JSON
        compact; the master converts back to seconds for scoring)."""
        with self._lock:
            steps = list(self._steps)
            records = list(self._records)
        if not steps:
            return {"steps": 0}
        s = sorted(steps)
        wall = sum(steps)
        return {
            "steps": len(steps),
            "step_p50_ms": round(1e3 * quantile_sorted(s, 0.5), 3),
            "step_p90_ms": round(1e3 * quantile_sorted(s, 0.9), 3),
            "step_max_ms": round(1e3 * s[-1], 3),
            "records_per_s": round(sum(records) / wall, 3) if wall > 0 else 0.0,
        }


# ---------------------------------------------------------------------- #
# master side


def median(values: List[float]) -> float:
    """Plain median (0.0 for empty) — the ONE center statistic the scorer
    and the rollup report share; diverging implementations would let the
    threshold math and the exported median_step_time_s disagree."""
    if not values:
        return 0.0
    s = sorted(values)
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_scores(values: List[float], *, abs_floor_s: float = 1e-3,
                  rel_floor: float = 0.05) -> List[float]:
    """Robust z-scores via median/MAD. The scale gets two floors — an
    absolute one (sub-millisecond MADs on a quiet fleet would make micro-
    jitter look like a 100-sigma event) and one relative to the median
    (5%: a fleet whose steps agree to within measurement noise has MAD ~ 0,
    and dividing by it would flag everyone). 1.4826 makes MAD consistent
    with a Gaussian sigma, so the threshold reads in sigmas."""
    if not values:
        return []
    med = median(values)
    mad = median([abs(v - med) for v in values])
    scale = max(1.4826 * mad, rel_floor * med, abs_floor_s)
    return [(v - med) / scale for v in values]


class ClusterHealth:
    """Fleet-level interpretation of the per-worker health records
    `Membership` accumulates from heartbeat telemetry.

    `update()` (the master's wait-loop calls it every poll, next to
    `membership.reap()`) recomputes the rollup: which alive workers have
    FRESH telemetry, the fleet median/MAD of their step-time p50s, robust
    scores, and the straggler set — a worker is a straggler when its score
    clears `threshold` sigmas AND its p50 is at least `min_ratio` x the
    median (the ratio gate keeps a statistically-odd-but-harmless 2%
    deviation from paging anyone). Detection is edge-triggered: the
    `cluster.straggler` event and the hooks fire once at onset (and
    `cluster.straggler_cleared` at recovery), not every poll.

    Hooks are the elasticity-decision seam: the closed-loop autoscaler
    (master/autoscaler.py) records straggler onsets here and decides on
    the wait poll; the built-in hook just logs. A hook that raises is
    logged + counted (edl_hook_errors_total{source=cluster_health}) and
    dropped from the failing invocation — scoring must survive its
    consumers.
    """

    def __init__(
        self,
        membership,
        *,
        threshold: float = 3.0,
        min_ratio: float = 1.5,
        min_workers: int = 3,
        stale_after_s: float = 30.0,
        on_straggler: Optional[Callable[[Dict], None]] = None,
    ):
        self._membership = membership
        self.threshold = float(threshold)
        self.min_ratio = float(min_ratio)
        # the scoring quorum (--straggler_quorum; config validates >= 2
        # at boot, this floor backstops direct constructions): with 2
        # reporters the median IS one of them, but the min_ratio gate
        # still decides "who is slow" — a 2-worker fleet must be able to
        # flag its straggler; with 1 the question is undecidable
        self.min_workers = max(2, int(min_workers))
        self.stale_after_s = float(stale_after_s)
        self._hooks: List[Callable[[Dict], None]] = [self._log_action]
        if on_straggler is not None:
            self._hooks.append(on_straggler)
        self._lock = threading.Lock()
        self._straggling: Dict[int, Dict] = {}       # guarded_by: _lock
        self._last: Dict = {                          # guarded_by: _lock
            "ts": 0.0,
            "workers_reporting": 0,
            "straggler_count": 0,
            "stragglers": [],
        }

    def add_hook(self, cb: Callable[[Dict], None]) -> None:
        """cb(straggler_info) fires once per straggler ONSET."""
        self._hooks.append(cb)

    @staticmethod
    def _log_action(info: Dict) -> None:
        logger.warning(
            "STRAGGLER: worker %s step p50 %.1fms vs fleet median %.1fms "
            "(score %.1f); no action taken (log-only policy)",
            info.get("worker_id"), 1e3 * info.get("step_time_p50_s", 0.0),
            1e3 * info.get("median_step_time_s", 0.0), info.get("score", 0.0),
        )

    # ------------------------------------------------------------------ #

    def update(self, now: Optional[float] = None) -> Dict:
        """Recompute the rollup; returns the snapshot. Never raises (the
        master's wait loop calls this unconditionally)."""
        try:
            return self._update(now)
        except Exception:
            logger.exception("cluster health rollup failed; keeping last")
            return self.snapshot()

    def _update(self, now: Optional[float] = None) -> Dict:
        now = time.time() if now is None else now
        records = self._membership.health_snapshot()
        fresh = [
            r for r in records
            if now - float(r.get("updated_at") or 0.0) <= self.stale_after_s
            and float(r.get("step_p50_ms") or 0.0) > 0.0
        ]
        p50s = [float(r["step_p50_ms"]) / 1e3 for r in fresh]
        # scoring needs a quorum: with 2 reporters the median IS one of
        # them and "who is slow" is undecidable
        scorable = len(fresh) >= self.min_workers
        snap: Dict = {
            "ts": now,
            "workers_alive": len(records),
            "workers_reporting": len(fresh),
            "straggler_count": 0,
            "stragglers": [],
            "median_step_time_s": 0.0,
            "max_step_time_s": 0.0,
            "skew": 1.0,
            "slowest_worker": -1,
            "fastest_worker": -1,
        }
        stragglers: List[Dict] = []
        if p50s:
            med = median(p50s)
            snap["median_step_time_s"] = round(med, 6)
            snap["max_step_time_s"] = round(max(p50s), 6)
            if med > 0:
                snap["skew"] = round(max(p50s) / med, 4)
            slowest = max(fresh, key=lambda r: float(r["step_p50_ms"]))
            fastest = min(fresh, key=lambda r: float(r["step_p50_ms"]))
            snap["slowest_worker"] = int(slowest.get("worker_id", -1))
            snap["fastest_worker"] = int(fastest.get("worker_id", -1))
            if scorable:
                scores = robust_scores(p50s)
                # quorum-2 fleets: with exactly two reporters the
                # median/MAD score is structurally capped at ~0.67 sigma
                # (each value is equidistant from their midpoint), so the
                # sigma threshold alone could NEVER fire — the min_ratio
                # gate decides instead (p50 >= 1.5x the pair median means
                # >= 3x the peer). More reporters restore the full
                # two-gate rule.
                pair = len(fresh) == 2
                for r, x, score in zip(fresh, p50s, scores):
                    if (score >= self.threshold or pair) \
                            and x >= self.min_ratio * med:
                        info = {
                            "worker_id": int(r.get("worker_id", -1)),
                            "worker_name": str(r.get("name", "")),
                            "score": round(score, 2),
                            "step_time_p50_s": round(x, 6),
                            "median_step_time_s": round(med, 6),
                            "phase": str(r.get("phase", "")),
                        }
                        # the step profiler's per-phase breakdown + memory
                        # watermarks (observability/profile.py), when the
                        # worker's payload carried them: the difference
                        # between "worker 3 is slow" and "worker 3 is
                        # blocked on its input pipeline"
                        for key in _PROFILE_KEYS:
                            if key in r:
                                info[key] = r[key]
                        stragglers.append(info)

        # "Cleared" must mean SCORED HEALTHY (or left the fleet) — not
        # "we lost the ability to score". A flagged worker whose telemetry
        # went stale, or a fleet that dropped below quorum mid-incident,
        # carries the flag forward: emitting cleared there would close the
        # incident spuriously and double-count the onset (event + hooks)
        # when scoring resumes.
        alive_ids = {int(r.get("worker_id", -1)) for r in records}
        fresh_ids = {int(r.get("worker_id", -1)) for r in fresh}
        with self._lock:
            previous = dict(self._straggling)
            current = {info["worker_id"]: info for info in stragglers}
            for wid, info in previous.items():
                if wid not in current and wid in alive_ids and (
                    not scorable or wid not in fresh_ids
                ):
                    current[wid] = info      # still flagged, not re-scorable
            onset = [
                info for wid, info in current.items() if wid not in previous
            ]
            cleared = [
                info for wid, info in previous.items() if wid not in current
            ]
            self._straggling = current
            snap["scorable"] = scorable
            snap["straggler_count"] = len(current)
            snap["stragglers"] = sorted(
                current.values(), key=lambda i: i["worker_id"]
            )
            self._last = snap

        _CL_REPORTING.set(snap["workers_reporting"])
        _CL_SKEW.set(snap["skew"])
        _CL_STRAGGLERS.set(snap["straggler_count"])
        _CL_SLOWEST.set(snap["slowest_worker"])
        _CL_FASTEST.set(snap["fastest_worker"])
        _CL_MEDIAN.set(snap["median_step_time_s"])

        # events + hooks OUTSIDE the lock (trace emission is file I/O —
        # edl-lint EDL402 codifies exactly this)
        for info in onset:
            _CL_EVENTS.inc()
            tracing.event("cluster.straggler", **info)
            for hook in self._hooks:
                try:
                    hook(dict(info))
                except Exception:
                    # swallowed (scoring must survive its consumers) but
                    # never dark: counted + named (observability/hooks.py)
                    from elasticdl_tpu.observability.hooks import (
                        observe_hook_failure,
                    )

                    observe_hook_failure("cluster_health", hook, logger)
        for info in cleared:
            tracing.event(
                "cluster.straggler_cleared", worker_id=info["worker_id"],
            )
            logger.info(
                "straggler cleared: worker %s scored back inside the fleet "
                "envelope (or left the fleet)", info["worker_id"],
            )
        return snap

    def snapshot(self, now: Optional[float] = None) -> Dict:
        """The last computed rollup (cheap; /healthz serves this — a
        scrape must never trigger a recompute, and scoring never depends
        on the scrape surface being alive). `snapshot_age_s` stamps how
        stale the cached rollup is AT SERVE TIME (-1 = never computed):
        a scraper reading a wedged master's /healthz must be able to
        tell a live rollup from one frozen at the wedge."""
        with self._lock:
            snap = dict(self._last)
        ts = float(snap.get("ts") or 0.0)
        now = time.time() if now is None else now
        snap["snapshot_age_s"] = (
            round(max(0.0, now - ts), 3) if ts > 0 else -1.0
        )
        return snap
