"""gRPC service glue for the Master service, written against grpc's generic
handler API (this image has protoc for messages but no grpcio-tools plugin,
so the service bindings that `elasticdl_pb2_grpc.py` would contain in the
reference are spelled out here by hand).

Reference parity: the generated MasterServicer/MasterStub pair of
elasticdl/proto/elasticdl.proto.
"""

from __future__ import annotations

from typing import Any

import grpc

from elasticdl_tpu.common.constants import GRPC
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

SERVICE_NAME = "elasticdl_tpu.Master"

# rpc name -> (request type, response type)
_RPCS = {
    "RegisterWorker": (pb.RegisterWorkerRequest, pb.RegisterWorkerResponse),
    "GetTask": (pb.GetTaskRequest, pb.GetTaskResponse),
    "ReportTaskResult": (pb.ReportTaskResultRequest, pb.ReportTaskResultResponse),
    "ReportEvaluationMetrics": (
        pb.ReportEvaluationMetricsRequest,
        pb.ReportEvaluationMetricsResponse,
    ),
    "Heartbeat": (pb.HeartbeatRequest, pb.HeartbeatResponse),
    "GetJobStatus": (pb.Empty, pb.JobStatusResponse),
}


def add_master_servicer(server: grpc.Server, servicer: Any) -> None:
    """Register a servicer object exposing methods named after the rpcs."""
    handlers = {}
    for name, (req_t, _resp_t) in _RPCS.items():
        method = getattr(servicer, name)
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            method,
            request_deserializer=req_t.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


class MasterStub:
    """Client stub for the Master service."""

    def __init__(self, channel: grpc.Channel):
        self._methods = {}
        for name, (req_t, resp_t) in _RPCS.items():
            self._methods[name] = channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_t.FromString,
            )

    def __getattr__(self, name: str):
        try:
            return self._methods[name]
        except KeyError as e:
            raise AttributeError(name) from e


def make_channel(addr: str) -> grpc.Channel:
    return grpc.insecure_channel(addr, options=GRPC.OPTIONS)


def make_server(max_workers: int = 32) -> grpc.Server:
    from concurrent import futures

    return grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers), options=GRPC.OPTIONS
    )
