"""gRPC service glue for the Master service, written against grpc's generic
handler API (this image has protoc for messages but no grpcio-tools plugin,
so the service bindings that `elasticdl_pb2_grpc.py` would contain in the
reference are spelled out here by hand).

Reference parity: the generated MasterServicer/MasterStub pair of
elasticdl/proto/elasticdl.proto — plus the hardening the reference never
had: every client call carries a deadline, idempotent RPCs retry with
exponential backoff + jitter, and a circuit breaker stops a worker from
hammering a dead master (RetryingMasterStub). Fault-injection sites
(`rpc.<method>` / `rpc.<method>.recv`, common/faults.py) wrap each send so
chaos schedules can drop/delay/lose-response any call deterministically.
"""

from __future__ import annotations

import random
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import grpc

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.constants import GRPC
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = default_logger(__name__)

SERVICE_NAME = "elasticdl_tpu.Master"

#: metadata keys of the master-generation handshake (master/journal.py).
#: The generation is a monotonic counter persisted in the control-plane
#: journal header and bumped on every master restart. It rides gRPC
#: metadata (this image cannot regenerate proto messages): the server
#: stamps its generation onto every response's trailing metadata; clients
#: claim the generation they believe current on every call, and the
#: servicer fences mismatches with FAILED_PRECONDITION so a report leased
#: under a pre-crash master can never be double-counted by its successor.
GENERATION_KEY = "edl-master-generation"
#: marks a RegisterWorker as a RECONNECT of an existing member (idempotent
#: re-register; no membership-version bump for a live worker) rather than
#: a fresh join
REREGISTER_KEY = "edl-reregister"

# control-plane wire metrics (scraped via /metrics; docs/observability.md)
_reg = default_registry()
_RPC_CALLS = _reg.counter(
    "edl_rpc_client_calls_total",
    "client RPC attempts (per method, incl. retries)", labels=("method",))
_RPC_RETRIES = _reg.counter(
    "edl_rpc_client_retries_total",
    "retry attempts after a retryable failure", labels=("method",))
_RPC_FAILURES = _reg.counter(
    "edl_rpc_client_failures_total",
    "failed RPC attempts (any error)", labels=("method",))
_RPC_DEADLINE = _reg.counter(
    "edl_rpc_client_deadline_exceeded_total",
    "attempts that hit their deadline", labels=("method",))
_BREAKER_OPEN = _reg.gauge(
    "edl_rpc_breaker_open", "1 while the master circuit breaker is open")
_BREAKER_TRIPS = _reg.counter(
    "edl_rpc_breaker_trips_total", "circuit-breaker open transitions")
_BREAKER_RESETS = _reg.counter(
    "edl_rpc_breaker_reset_total",
    "breaker resets by a successful master-generation handshake")
_CHANNEL_REFRESHES = _reg.counter(
    "edl_rpc_channel_refreshes_total",
    "client channels rebuilt after repeated transport failures")
_RPC_LATENCY = _reg.histogram(
    "edl_rpc_client_latency_seconds",
    "successful-call wall latency", labels=("method",))

# rpc name -> (request type, response type)
_RPCS = {
    "RegisterWorker": (pb.RegisterWorkerRequest, pb.RegisterWorkerResponse),
    "GetTask": (pb.GetTaskRequest, pb.GetTaskResponse),
    "ReportTaskResult": (pb.ReportTaskResultRequest, pb.ReportTaskResultResponse),
    "ReportEvaluationMetrics": (
        pb.ReportEvaluationMetricsRequest,
        pb.ReportEvaluationMetricsResponse,
    ),
    "Heartbeat": (pb.HeartbeatRequest, pb.HeartbeatResponse),
    "GetJobStatus": (pb.Empty, pb.JobStatusResponse),
    "GetEmbeddingShardMap": (
        pb.GetEmbeddingShardMapRequest,
        pb.GetEmbeddingShardMapResponse,
    ),
    "ReportEmbeddingReshard": (
        pb.ReportEmbeddingReshardRequest,
        pb.ReportEmbeddingReshardResponse,
    ),
}

#: methods whose server-side handling opens a span when the client sent a
#: trace context (Heartbeat excluded: 1/s/worker would drown the timeline)
_TRACED_SERVER_RPCS = frozenset(_RPCS) - {"Heartbeat"}


def rpc_site(name: str) -> str:
    """Fault-injection site for an RPC: snake_case under the rpc. prefix
    (GetTask -> rpc.get_task)."""
    return "rpc." + re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


@dataclass(frozen=True)
class RpcPolicy:
    """Per-RPC client behavior: default deadline, retry eligibility.

    `idempotent` means a retry after an ambiguous failure (deadline, lost
    response) cannot change job state a second time. Only those RPCs are
    retried; for the rest a retry is the caller's decision because it needs
    protocol context:

      RegisterWorker          NOT idempotent — re-registering allocates a
                              fresh membership version (and possibly id)
      GetTask                 NOT idempotent — a lost response leaves a task
                              leased; retrying would lease a second one and
                              expire the first into a spurious requeue
      ReportTaskResult        NOT idempotent at this layer — the dispatcher
                              dedupes, but the duplicate returns
                              accepted=False, which the preemption-drain
                              protocol treats as a rejection (it would
                              delete the drain checkpoint it must keep)
      Heartbeat               NOT idempotent — the servicer consumes the
                              one-shot should_checkpoint flag on read, so a
                              retry after a lost response would report
                              should_checkpoint=False and silently swallow
                              a master-requested (resize-quiesce)
                              checkpoint. The heartbeat LOOP is the retry
                              mechanism: the next beat arrives in
                              worker_heartbeat_s anyway.
      ReportEvaluationMetrics idempotent — the evaluation service dedupes
                              by task_id and drops repeats silently
      GetJobStatus            idempotent — read-only
    """

    timeout_s: float
    idempotent: bool
    max_attempts: int = 3


DEFAULT_POLICIES: Dict[str, RpcPolicy] = {
    "RegisterWorker": RpcPolicy(timeout_s=30.0, idempotent=False),
    "GetTask": RpcPolicy(timeout_s=30.0, idempotent=False),
    "ReportTaskResult": RpcPolicy(timeout_s=30.0, idempotent=False),
    "ReportEvaluationMetrics": RpcPolicy(timeout_s=30.0, idempotent=True),
    "Heartbeat": RpcPolicy(timeout_s=10.0, idempotent=False),
    "GetJobStatus": RpcPolicy(timeout_s=10.0, idempotent=True),
    # embedding tier control plane: the map read is a pure read; the
    # reshard confirm is idempotent at the ShardMapOwner (re-confirming
    # an already-confirmed shard — or a whole already-committed plan —
    # changes nothing), so both retry safely
    "GetEmbeddingShardMap": RpcPolicy(timeout_s=10.0, idempotent=True),
    "ReportEmbeddingReshard": RpcPolicy(timeout_s=30.0, idempotent=True),
}


def jittered(seconds: float, rng: Optional[random.Random] = None) -> float:
    """An interval with full spread jitter: ``uniform(0.5, 1.5) * base``.

    The polling/heartbeat twin of the stub's retry backoff jitter (EDL304):
    a swarm of workers relaunched together — or unblocked together by a
    master restart or a rescale settling — would otherwise beat and
    re-poll in phase forever, hitting the master as one synchronized herd
    every interval. Every periodic control-plane sleep (heartbeat loops,
    WAIT backoffs, lease re-polls) goes through here so the fleet's
    arrivals stay spread."""
    return max(0.0, seconds) * (rng or random).uniform(0.5, 1.5)


class MasterUnreachableError(ConnectionError):
    """Raised fast (no wire traffic) while the circuit breaker is open."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker shared by all of a stub's RPCs.

    After `failure_threshold` consecutive failures the circuit opens: calls
    fail immediately with MasterUnreachableError for `cooldown_s`, then ONE
    probe call is let through (half-open); its outcome closes or re-opens
    the circuit. This keeps a worker from burning its master-unreachable
    grace window inside per-call connect timeouts against a dead address —
    the wall-clock-based `_master_unreachable` exit logic in the worker
    still makes the kill decision; the breaker just makes the failing
    window cheap and the log honest.
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 10.0,
                 telemetry: bool = True):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        # telemetry=False reuses the state machine without the
        # master-breaker gauges/events/log lines (the embedding data
        # plane keeps per-owner breakers and its own edl_emb_owner_*
        # metrics — a partitioned owner must not read as a master
        # outage on edl_rpc_breaker_open, nor close it back to 0)
        self._telemetry = telemetry
        # consecutive_failures is read lock-free by RetryingMasterStub's
        # error message (a snapshot for humans, not a decision input)
        self.consecutive_failures = 0
        self._opened_at: Optional[float] = None      # guarded_by: _lock
        self._probe_in_flight = False                # guarded_by: _lock
        # shared by the worker's heartbeat thread and main task loop: the
        # counter increment and the half-open single-probe admission are
        # read-modify-write and need the lock to stay exact
        self._lock = threading.Lock()

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._opened_at is not None

    def allow(self) -> bool:
        with self._lock:
            if self._opened_at is None:
                return True
            if (
                time.monotonic() - self._opened_at >= self.cooldown_s
                and not self._probe_in_flight
            ):
                # half-open: admit one probe; concurrent callers keep
                # failing fast until the probe resolves
                self._probe_in_flight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            reopened = self._opened_at is not None
            self.consecutive_failures = 0
            self._opened_at = None
            self._probe_in_flight = False
        if reopened and self._telemetry:
            _BREAKER_OPEN.set(0)
            tracing.event("rpc.breaker_closed")
            logger.info("master circuit closed again (probe succeeded)")

    def reset(self) -> bool:
        """Clear ALL breaker state (close the circuit, zero the failure
        count, release any probe slot). The generation-handshake hook: a
        stale-generation rejection proves the master is back (the fence is
        an application answer riding a healthy transport), so treating it
        as one more transport failure would hold the circuit open forever
        against a live master. Returns True when anything was cleared."""
        with self._lock:
            dirty = (
                self._opened_at is not None
                or self.consecutive_failures > 0
                or self._probe_in_flight
            )
            self.consecutive_failures = 0
            self._opened_at = None
            self._probe_in_flight = False
        if dirty and self._telemetry:
            _BREAKER_OPEN.set(0)
            _BREAKER_RESETS.inc()
            tracing.event("rpc.breaker_reset")
            logger.info("master circuit reset (generation handshake)")
        return dirty

    def record_failure(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            self._probe_in_flight = False
            opened_now = False
            if self._opened_at is not None:
                self._opened_at = time.monotonic()  # re-open: restart cooldown
            elif self.consecutive_failures >= self.failure_threshold:
                self._opened_at = time.monotonic()
                opened_now = True
            failures = self.consecutive_failures
        if opened_now and self._telemetry:
            _BREAKER_OPEN.set(1)
            _BREAKER_TRIPS.inc()
            tracing.event("rpc.breaker_open", consecutive_failures=failures)
            logger.warning(
                "master circuit OPEN after %d consecutive RPC failures; "
                "failing fast for %.1fs between probes",
                failures, self.cooldown_s,
            )


def _traced_handler(
    name: str, method: Callable, generation_fn: Optional[Callable[[], int]] = None
) -> Callable:
    """Wrap a servicer method so an incoming trace context (gRPC metadata
    set by RetryingMasterStub) re-opens on the handler thread: the worker's
    span becomes the parent of a server-side `rpc.server.<method>` span,
    and one resize reads as one timeline across both roles.

    When `generation_fn` yields a nonzero master generation, it is stamped
    onto the response's trailing metadata — the server half of the
    generation handshake (RetryingMasterStub adopts it client-side)."""
    span_name = "rpc.server." + rpc_site(name)[len("rpc."):]

    def stamped(request, context):
        gen = generation_fn() if generation_fn is not None else 0
        if gen:
            try:
                context.set_trailing_metadata(((GENERATION_KEY, str(gen)),))
            except Exception:
                # the handshake is advisory on exotic contexts (in-process
                # fakes without trailing-metadata support); the RPC itself
                # must still be served: edl-lint: disable=EDL303
                pass
        return method(request, context)

    def handler(request, context):
        md = {}
        try:
            md = {k: v for k, v in (context.invocation_metadata() or ())}
        except Exception:
            # metadata is observability-only; a context that can't supply
            # it still serves the RPC: edl-lint: disable=EDL303
            pass
        trace_id = md.get(tracing.TRACE_ID_KEY)
        if not trace_id or name not in _TRACED_SERVER_RPCS:
            return stamped(request, context)
        with tracing.adopt(trace_id, md.get(tracing.SPAN_ID_KEY)):
            with tracing.span(span_name):
                return stamped(request, context)

    return handler


def add_master_servicer(server: grpc.Server, servicer: Any) -> None:
    """Register a servicer object exposing methods named after the rpcs."""
    handlers = {}
    # the generation is read per call, not captured: a MasterServicer built
    # before its journal replayed (tests) still stamps the final value
    generation_fn = (
        (lambda: int(getattr(servicer, "generation", 0) or 0))
        if hasattr(servicer, "generation") else None
    )
    for name, (req_t, _resp_t) in _RPCS.items():
        method = _traced_handler(name, getattr(servicer, name), generation_fn)
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            method,
            request_deserializer=req_t.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


class MasterStub:
    """Client stub for the Master service."""

    def __init__(self, channel: grpc.Channel):
        self._methods = {}
        for name, (req_t, resp_t) in _RPCS.items():
            self._methods[name] = channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_t.FromString,
            )

    def __getattr__(self, name: str):
        try:
            return self._methods[name]
        except KeyError as e:
            raise AttributeError(name) from e


class RetryingMasterStub:
    """MasterStub hardened for the worker side of an elastic job.

    Every call gets a deadline (the per-RPC policy default, or an explicit
    `timeout=`); idempotent RPCs (see RpcPolicy) retry transient failures
    with exponential backoff + full jitter; a shared CircuitBreaker fails
    fast against a dead master. With no fault schedule active and no
    failures, the only behavior change over the bare stub is the deadline.

    `on_success` (if given) runs after every successful call — the worker
    wires its `_last_master_ok` clock here so the master-unreachable exit
    logic sees every RPC, not just the two loops that updated it by hand.
    """

    #: failures worth retrying: transport errors and injected faults. An
    #: INVALID_ARGUMENT-style local error also lands here — acceptable,
    #: since retries are bounded and only on idempotent calls.
    RETRYABLE = (grpc.RpcError, faults.FaultInjected)

    def __init__(
        self,
        channel: grpc.Channel,
        policies: Optional[Dict[str, RpcPolicy]] = None,
        on_success: Optional[Callable[[], None]] = None,
        breaker: Optional[CircuitBreaker] = None,
        backoff_base_s: float = 0.2,
        backoff_max_s: float = 5.0,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        stub: Any = None,
        channel_factory: Optional[Callable[[], grpc.Channel]] = None,
        refresh_after: int = 3,
    ):
        self._stub = stub if stub is not None else MasterStub(channel)
        # Bounded reconnect loop for UNAVAILABLE-during-restart: a gRPC
        # channel whose subchannel wedged against a restarted master (stale
        # backoff state, dead reuseport flow) can report connect failures
        # long after the master is back. With a channel_factory, every
        # `refresh_after` consecutive transport failures the stub REBUILDS
        # the channel — fresh sockets, fresh resolver — instead of trusting
        # the wedged one forever. The workers wire this; injected test
        # stubs don't need it.
        self._channel = channel
        self._channel_factory = channel_factory
        self._refresh_after = max(1, refresh_after)
        self._transport_failures = 0          # guarded_by: _refresh_lock
        self._last_refresh = 0.0              # guarded_by: _refresh_lock
        self._refresh_lock = threading.Lock()
        self._policies = dict(DEFAULT_POLICIES)
        if policies:
            self._policies.update(policies)
        self._on_success = on_success
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        # The master generation this client believes current (None until
        # the first handshake). Claimed on every call as gRPC metadata so
        # the servicer can fence pre-restart stragglers; adopted from the
        # server's trailing metadata. The OWNER (worker/cohort) clears it
        # to None before a re-register — a generation-free RegisterWorker
        # is the handshake that learns the new one.
        self.generation: Optional[int] = None
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep

    def _backoff(self, attempt: int) -> float:
        """Exponential with full jitter: uniform(0, base * 2^attempt]."""
        cap = min(self._backoff_max_s, self._backoff_base_s * (2 ** attempt))
        return cap * self._rng.uniform(0.1, 1.0)

    def __getattr__(self, name: str):
        if name not in _RPCS:
            raise AttributeError(name)
        policy = self._policies.get(name) or RpcPolicy(30.0, False)
        site = rpc_site(name)
        # the closure below is cached on the instance (end of this method):
        # __getattr__ runs once per RPC name, not once per call

        def call(request, timeout: Optional[float] = None, metadata=None):
            attempts = policy.max_attempts if policy.idempotent else 1
            deadline = timeout if timeout is not None else policy.timeout_s
            last: Optional[BaseException] = None
            for attempt in range(attempts):
                if not self.breaker.allow():
                    raise MasterUnreachableError(
                        f"{name}: circuit open after "
                        f"{self.breaker.consecutive_failures} consecutive "
                        "failures"
                    )
                t_call = time.perf_counter()
                # resolved per attempt, not captured: a channel refresh
                # swaps self._stub and the next attempt must use the NEW
                # multicallables, not a closed channel's
                method = getattr(self._stub, name)
                try:
                    _RPC_CALLS.inc(method=name)
                    faults.fire(site)
                    # the active trace context (a rescale span, a reform
                    # boot) rides the wire as gRPC metadata so the master's
                    # handler joins the same timeline — alongside the
                    # generation claim the servicer fences on; no metadata,
                    # no kwarg (injected test stubs only take
                    # (request, timeout))
                    md = list(tracing.rpc_metadata() or ())
                    if self.generation is not None:
                        md.append((GENERATION_KEY, str(self.generation)))
                    if metadata:
                        md.extend(metadata)
                    # with_call (real grpc multicallables only) exposes the
                    # server's trailing metadata — the generation handshake
                    with_call = getattr(method, "with_call", None)
                    rpc_call = None
                    if with_call is not None:
                        resp, rpc_call = with_call(
                            request, timeout=deadline, metadata=md or None
                        )
                    elif md:
                        resp = method(request, timeout=deadline, metadata=md)
                    else:
                        resp = method(request, timeout=deadline)
                    # lost-response injection: the server DID process the
                    # call; the caller never hears back
                    faults.fire(site + ".recv")
                except self.RETRYABLE as e:
                    if is_stale_generation(e):
                        # the master is BACK, under a new generation: this
                        # is an application-level fence on a healthy
                        # transport. Clear the breaker (it would otherwise
                        # re-open on every fenced probe and never close)
                        # and surface the rejection — the caller owns the
                        # re-register handshake.
                        self.breaker.reset()
                        raise
                    last = e
                    self.breaker.record_failure()
                    self._note_transport_failure()
                    _RPC_FAILURES.inc(method=name)
                    if _is_deadline_exceeded(e):
                        _RPC_DEADLINE.inc(method=name)
                    if attempt + 1 < attempts:
                        delay = self._backoff(attempt)
                        _RPC_RETRIES.inc(method=name)
                        tracing.event(
                            "rpc.retry", method=name, attempt=attempt + 1,
                            backoff_s=round(delay, 4),
                        )
                        logger.warning(
                            "%s failed (%s); retry %d/%d in %.2fs",
                            name, _err_summary(e), attempt + 1,
                            attempts - 1, delay,
                        )
                        self._sleep(delay)
                    continue
                except BaseException:
                    # non-retryable error (closed channel, bad request
                    # object, ...): record it so a half-open probe never
                    # leaves _probe_in_flight latched — otherwise the
                    # circuit would stay open forever against a healthy
                    # master — then surface it unchanged
                    self.breaker.record_failure()
                    _RPC_FAILURES.inc(method=name)
                    raise
                self.breaker.record_success()
                with self._refresh_lock:
                    self._transport_failures = 0
                if rpc_call is not None:
                    self._adopt_generation(rpc_call)
                _RPC_LATENCY.observe(
                    time.perf_counter() - t_call, method=name)
                if self._on_success is not None:
                    self._on_success()
                return resp
            raise last

        setattr(self, name, call)
        return call

    def _note_transport_failure(self) -> None:
        """Count a real wire failure; every `refresh_after`-th in a row
        rebuilds the channel (when a factory was wired). Rate-limited so
        the worker's heartbeat and task threads don't thrash a rebuild."""
        if self._channel_factory is None:
            return
        with self._refresh_lock:
            self._transport_failures += 1
            now = time.monotonic()
            if (
                self._transport_failures % self._refresh_after != 0
                or now - self._last_refresh < 2.0
            ):
                return
            self._last_refresh = now
            old = self._channel
            try:
                self._channel = self._channel_factory()
                # swap the stub LAST: concurrent calls resolve their
                # multicallable per attempt off self._stub
                self._stub = MasterStub(self._channel)
            except Exception:
                logger.exception("channel refresh failed; keeping old channel")
                self._channel = old
                return
            failures = self._transport_failures
        _CHANNEL_REFRESHES.inc()
        tracing.event("rpc.channel_refresh", consecutive_failures=failures)
        logger.warning(
            "rebuilt master channel after %d consecutive transport "
            "failures (stale subchannel state survives a master restart)",
            failures,
        )
        # The old channel is NOT force-closed: the stub is shared between
        # threads (heartbeat + task loop), and Channel.close() CANCELS every
        # in-flight RPC on it — a healthy non-idempotent ReportTaskResult
        # racing the refresh would be killed and never retried, expiring the
        # lease and re-running the task. Dropping the reference lets grpc
        # tear it down once the last in-flight call off it completes.

    def _adopt_generation(self, rpc_call: Any) -> None:
        """Read the master generation off a successful call's trailing
        metadata. Adopting a CHANGED generation is the handshake landing:
        the breaker is reset (edl_rpc_breaker_reset_total) so the restart's
        accumulated failures stop penalizing the recovered master."""
        try:
            trailing = rpc_call.trailing_metadata() or ()
        except Exception:
            # trailing metadata is the advisory half of the handshake;
            # a call object without it is not an error:
            # edl-lint: disable=EDL303
            return
        gen = None
        for k, v in trailing:
            if k == GENERATION_KEY:
                try:
                    gen = int(v)
                except (TypeError, ValueError):
                    return
                break
        if not gen:
            return
        prev, self.generation = self.generation, gen
        if prev is not None and prev != gen:
            self.breaker.reset()
            tracing.event(
                "rpc.generation_handshake", prev_generation=prev,
                generation=gen,
            )
            logger.warning(
                "master generation handshake: %d -> %d (master restarted)",
                prev, gen,
            )


def is_stale_generation(e: BaseException) -> bool:
    """True for the servicer's stale-master-generation fence: a
    FAILED_PRECONDITION whose details name the generation. Callers react by
    re-registering (clear `stub.generation`, RegisterWorker with
    REREGISTER_KEY), then re-leasing — never by treating the master as
    dead."""
    code = getattr(e, "code", None)
    details = getattr(e, "details", None)
    try:
        return (
            callable(code)
            and code() == grpc.StatusCode.FAILED_PRECONDITION
            and callable(details)
            and "generation" in str(details())
        )
    except Exception:
        # classification-only: an exotic error object is simply not a
        # stale-generation fence: edl-lint: disable=EDL303
        return False


def register_with_retry(
    stub: "RetryingMasterStub",
    *,
    name: str,
    preferred_id: int,
    window_s: float,
    shutdown: threading.Event,
    what: str = "worker",
    member_names=(),
    data_addr: str = "",
):
    """Boot-time registration hardened against a master that is down or
    RESTARTING right now (observed: a master crash with the registration
    handler already run server-side cancels the response — the join is
    journaled but this process never hears its id, and an unretried failure
    kills the whole worker, recovering only via the relaunch budget and
    leaving a ghost member). RegisterWorker is not blindly retriable (a
    duplicate plain join allocates a second id), so retries with a known
    ``preferred_id`` carry the REREGISTER marker: the successor master
    treats them as an idempotent reconnect of the journaled member.

    Bounded by the same clock that governs all master-unreachable
    decisions; ``window_s <= 0`` means that clock is DISABLED (config.py:
    "0 disables") — retry until ``shutdown`` fires, never give up on the
    master. Shared by worker.py and cohort.py so the handshake cannot
    diverge between the two worker flavors."""
    from elasticdl_tpu.observability import goodput as goodput_lib

    deadline = (time.monotonic() + window_s) if window_s > 0 else None
    attempt = 0
    ledger = goodput_lib.get_ledger()
    while True:
        request = pb.RegisterWorkerRequest(
            worker_name=name,
            preferred_id_plus_one=preferred_id + 1 if preferred_id >= 0 else 0,
            member_names=list(member_names),
            data_plane_addr=data_addr,
        )
        metadata = (
            ((REREGISTER_KEY, "1"),) if attempt and preferred_id >= 0 else None
        )
        try:
            return stub.RegisterWorker(request, timeout=30, metadata=metadata)
        except Exception as e:
            attempt += 1
            if is_stale_generation(e):
                # raced a restart mid-handshake: drop the adopted claim
                # and register fresh against the successor
                stub.generation = None
            elif deadline is not None and time.monotonic() >= deadline:
                raise
            logger.warning(
                "%s boot registration failed (attempt %d): %s; retrying",
                what, attempt, e,
            )
            # goodput: riding out a down/restarting master is the
            # `reconnect` category (the generation-fence window)
            with ledger.phase("reconnect"):
                shutdown.wait(random.uniform(0.5, 1.5))
            if shutdown.is_set():
                raise


def reregister(stub: "RetryingMasterStub", *, name: str, worker_id: int,
               member_names=(), data_addr: str = ""):
    """The reconnect handshake after a master restart: clear the stale
    generation claim (a generation-free RegisterWorker is what learns the
    new one from the response's trailing metadata), then re-register under
    the EXISTING worker id with the REREGISTER marker — the restarted
    master treats it as an idempotent reconnect of a replayed member, not
    a fresh join (no membership-version bump for a live worker, so the
    cohort does not re-form). Callers apply the response to their own
    state; shared by worker.py and cohort.py."""
    from elasticdl_tpu.observability import goodput as goodput_lib

    stub.generation = None
    # goodput: the re-register handshake is `reconnect` time — part of
    # the master-restart bill the fleet ledger totals
    with goodput_lib.get_ledger().phase("reconnect"):
        return stub.RegisterWorker(
            pb.RegisterWorkerRequest(
                worker_name=name, preferred_id_plus_one=worker_id + 1,
                member_names=list(member_names),
                data_plane_addr=data_addr,
            ),
            timeout=30,
            metadata=((REREGISTER_KEY, "1"),),
        )


def _is_deadline_exceeded(e: BaseException) -> bool:
    code = getattr(e, "code", None)
    try:
        return callable(code) and code() == grpc.StatusCode.DEADLINE_EXCEEDED
    except Exception:
        # classification-only (a metric label): an exotic error object
        # counts as not-a-deadline: edl-lint: disable=EDL303
        return False


def _err_summary(e: BaseException) -> str:
    code = getattr(e, "code", None)
    try:
        return str(code()) if callable(code) else repr(e)
    except Exception:
        return repr(e)


def make_channel(addr: str) -> grpc.Channel:
    return grpc.insecure_channel(addr, options=GRPC.OPTIONS)


def make_server(max_workers: int = 32) -> grpc.Server:
    from concurrent import futures

    return grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        # so_reuseport off: gRPC's default SO_REUSEPORT lets a successor
        # master "successfully" bind a port whose previous (crashed, not
        # yet fully closed) server still holds a listener in the reuseport
        # group — the kernel then keeps hashing existing clients' reconnect
        # flows onto the dead socket and they see connection-refused until
        # it finally closes. An exclusive bind fails HONESTLY (0 /
        # RuntimeError -> PortBindError -> retry) until the port is truly
        # free, which is what the master-restart path needs.
        options=GRPC.OPTIONS + [("grpc.so_reuseport", 0)],
    )
