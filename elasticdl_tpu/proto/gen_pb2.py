"""Regenerate ``elasticdl_tpu_pb2.py`` WITHOUT protoc.

This image ships the protobuf runtime but no protoc / grpcio-tools (the
constraint that previously pushed new wire surfaces onto gRPC metadata —
the generation handshake, the worker-stats payload). A pb2 module,
however, is nothing but a serialized ``FileDescriptorProto`` plus builder
boilerplate — and the runtime's ``descriptor_pb2`` can build that proto in
pure Python. This tool loads the CURRENT serialized descriptor from the
checked-in pb2, applies the schema additions declared in ``PATCHES``
below, and re-emits the module in the standard generated style (including
the ``_serialized_start/_end`` offsets, recomputed by locating each
message's serialized sub-descriptor inside the file bytes).

Keep ``elasticdl_tpu.proto`` — the human-readable source of truth — in
sync by hand; ``tests/test_master_servicer.py`` pins the fields this tool
adds so the two cannot drift silently.

Run from the repo root:

    python -m elasticdl_tpu.proto.gen_pb2

Proto3 back/forward compatibility does the rest: an old worker never sets
the new fields (defaults decode as absent), a new worker talking to an old
master sends fields the old descriptor skips as unknown.
"""

from __future__ import annotations

import os

from google.protobuf import descriptor_pb2

_HERE = os.path.dirname(os.path.abspath(__file__))
_PB2_PATH = os.path.join(_HERE, "elasticdl_tpu_pb2.py")

# (message, field name, field number, type, extras)
_SCALAR = {
    "int32": descriptor_pb2.FieldDescriptorProto.TYPE_INT32,
    "int64": descriptor_pb2.FieldDescriptorProto.TYPE_INT64,
    "string": descriptor_pb2.FieldDescriptorProto.TYPE_STRING,
    "float": descriptor_pb2.FieldDescriptorProto.TYPE_FLOAT,
    "bool": descriptor_pb2.FieldDescriptorProto.TYPE_BOOL,
    "bytes": descriptor_pb2.FieldDescriptorProto.TYPE_BYTES,
}


def _add_field(msg, name, number, ftype, *, repeated=False, type_name=""):
    if any(f.name == name for f in msg.field):
        return False
    f = msg.field.add()
    f.name = name
    f.number = number
    f.label = (
        descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
        if repeated else descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
    )
    if type_name:
        f.type = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
        f.type_name = type_name
    else:
        f.type = _SCALAR[ftype]
    f.json_name = _json_name(name)
    return True


def _json_name(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


def apply_patches(fd: descriptor_pb2.FileDescriptorProto) -> int:
    """The schema additions this repo has accrued post-protoc. Idempotent —
    re-running against an already-patched descriptor changes nothing."""
    msgs = {m.name: m for m in fd.message_type}
    changed = 0

    # Batched task leases: the worker asks for up to max_tasks in one
    # round-trip; the master answers with `tasks` (the legacy singular
    # `task` stays populated with the first lease for old workers).
    changed += _add_field(msgs["GetTaskRequest"], "max_tasks", 2, "int32")
    changed += _add_field(
        msgs["GetTaskResponse"], "tasks", 4, "",
        repeated=True, type_name=".elasticdl_tpu.Task",
    )

    # Cohort-aggregated membership: a leader registers its member
    # processes in the SAME RegisterWorker round-trip, and its single
    # heartbeat carries one MemberBeat per member — reap scans and
    # version bumps stay O(cohorts), telemetry stays O(workers).
    if "MemberBeat" not in msgs:
        mb = fd.message_type.add()
        mb.name = "MemberBeat"
        _add_field(mb, "worker_id", 1, "int32")
        _add_field(mb, "model_version", 2, "int32")
        # same compact JSON payload as the edl-worker-stats metadata
        # (observability/health.py encode_stats/decode_stats bounds apply)
        _add_field(mb, "stats_json", 3, "string")
        changed += 1
        msgs["MemberBeat"] = mb
    changed += _add_field(
        msgs["HeartbeatRequest"], "members", 3, "",
        repeated=True, type_name=".elasticdl_tpu.MemberBeat",
    )
    changed += _add_field(
        msgs["RegisterWorkerRequest"], "member_names", 3, "string",
        repeated=True,
    )
    changed += _add_field(
        msgs["RegisterWorkerResponse"], "member_ids", 4, "int32",
        repeated=True,
    )

    # Elastic sharded embedding tier (embedding/): the master owns the
    # id-sharded table map; workers fetch it (GetEmbeddingShardMap) and
    # confirm installed shard migrations (ReportEmbeddingReshard). The
    # tier's DATA plane (pull/push) is worker-to-worker and does not
    # cross the master — only the map does.
    def _new_msg(name, fields):
        if name in msgs:
            return 0
        m = fd.message_type.add()
        m.name = name
        for fname, num, ftype, kw in fields:
            _add_field(m, fname, num, ftype, **kw)
        msgs[name] = m
        return 1

    changed += _new_msg("EmbeddingTableSpec", [
        ("name", 1, "string", {}),
        # PADDED vocab rows (ops/embedding.padded_vocab — the checkpoint
        # geometry rule) and the deterministic init params that let any
        # owner materialize a fresh shard bit-identically
        ("vocab", 2, "int32", {}),
        ("dim", 3, "int32", {}),
        ("seed", 4, "int32", {}),
        ("init_scale", 5, "float", {}),
    ])
    changed += _new_msg("GetEmbeddingShardMapRequest", [
        ("worker_id", 1, "int32", {}),
    ])
    changed += _new_msg("GetEmbeddingShardMapResponse", [
        ("version", 1, "int32", {}),
        ("num_shards", 2, "int32", {}),
        # shard id -> owning worker id, dense
        ("shard_owners", 3, "int32", {"repeated": True}),
        ("tables", 4, "", {
            "repeated": True,
            "type_name": ".elasticdl_tpu.EmbeddingTableSpec",
        }),
        # a move plan is in flight (or was interrupted by a master
        # crash): clients conservatively requeue unacked pushes
        ("resharding", 5, "bool", {}),
    ])
    changed += _new_msg("ReportEmbeddingReshardRequest", [
        ("worker_id", 1, "int32", {}),
        ("version", 2, "int32", {}),
        ("shard_ids", 3, "int32", {"repeated": True}),
    ])
    changed += _new_msg("ReportEmbeddingReshardResponse", [
        ("accepted", 1, "bool", {}),
    ])

    # Closed-loop autoscaler (ISSUE 14, master/autoscaler.py): the
    # graceful-eviction drain handshake. The master sets `evict` on a
    # worker's heartbeat response; the worker drains through its
    # existing preempt path (drain checkpoint + preempted report — the
    # remainder requeues FRONT like a death) and exits EX_TEMPFAIL.
    # Old workers skip the unknown field and keep training (the policy
    # falls back to lease-expiry recovery); old masters never set it.
    changed += _add_field(msgs["HeartbeatResponse"], "evict", 7, "bool")

    # Read replicas (ISSUE 13): per-shard replica assignments ride the
    # same map response, flattened row-major at `replica_count` slots
    # per shard with -1 padding (proto3 has no repeated-of-repeated
    # without a message per row; a flat stride keeps old workers
    # oblivious — they skip unknown fields and read primaries only).
    changed += _add_field(
        msgs["GetEmbeddingShardMapResponse"], "replica_count", 6, "int32")
    changed += _add_field(
        msgs["GetEmbeddingShardMapResponse"], "shard_replicas", 7, "int32",
        repeated=True)

    # Cross-host embedding data plane (ISSUE 15, embedding/data_plane.py):
    # every worker serves its owning store over a per-worker gRPC
    # endpoint; peers reach it through the OWNER ADDRESS BOOK that rides
    # the shard-map response (addr_worker_ids[i] serves at addrs[i]).
    # Workers report their data-plane address at registration; old
    # workers never set it and are simply absent from the book (their
    # shards stay reachable in-process / via LocalTransport only).
    changed += _add_field(
        msgs["RegisterWorkerRequest"], "data_plane_addr", 4, "string")
    changed += _add_field(
        msgs["GetEmbeddingShardMapResponse"], "addr_worker_ids", 8, "int32",
        repeated=True)
    changed += _add_field(
        msgs["GetEmbeddingShardMapResponse"], "addrs", 9, "string",
        repeated=True)

    # Skew-adaptive layout (ISSUE 20, master/layout_controller.py): the
    # controller's worker-replicated ultra-hot id set rides the same map
    # response — GLOBAL ids (int64, same width as the pull path's id
    # space), sorted. Old workers skip the unknown field and keep
    # serving the plain sharded layout.
    changed += _add_field(
        msgs["GetEmbeddingShardMapResponse"], "hot_ids", 10, "int64",
        repeated=True)

    # Data-plane RPC payloads. Id vectors travel as raw little-endian
    # int32 bytes and row matrices as raw float32 bytes + a dim field
    # (one memcpy each way — repeated scalar varint packing would cost
    # real CPU at serving rates). Watermarks are int64: they count every
    # applied push over a job's lifetime.
    changed += _new_msg("EmbeddingPullRequest", [
        ("table", 1, "string", {}),
        ("shard", 2, "int32", {}),
        ("ids", 3, "bytes", {}),          # int32 LE, pow2-padded (-1)
        ("map_version", 4, "int32", {}),
        ("with_watermark", 5, "bool", {}),
        ("replica", 6, "bool", {}),
    ])
    changed += _new_msg("EmbeddingPullResponse", [
        ("rows", 1, "bytes", {}),         # float32 LE, (n_ids, dim)
        ("dim", 2, "int32", {}),
        ("wm", 3, "int64", {}),
    ])
    changed += _new_msg("EmbeddingPushRequest", [
        ("table", 1, "string", {}),
        ("shard", 2, "int32", {}),
        ("ids", 3, "bytes", {}),
        ("rows", 4, "bytes", {}),
        ("dim", 5, "int32", {}),
        ("client_id", 6, "string", {}),
        ("seq", 7, "int64", {}),
        ("map_version", 8, "int32", {}),
        ("scale", 9, "float", {}),
        ("with_watermark", 10, "bool", {}),
    ])
    changed += _new_msg("EmbeddingPushResponse", [
        ("applied", 1, "bool", {}),
        ("wm", 2, "int64", {}),
    ])
    changed += _new_msg("EmbeddingFetchShardRequest", [
        ("table", 1, "string", {}),
        ("shard", 2, "int32", {}),
        ("replica", 3, "bool", {}),
    ])
    changed += _new_msg("EmbeddingFetchShardResponse", [
        ("rows", 1, "bytes", {}),
        ("rows_n", 2, "int32", {}),
        ("dim", 3, "int32", {}),
        # exactly-once seq watermarks as the same compact JSON dict the
        # checkpoint .npz files carry — the fence TRAVELS with the shard
        ("applied_json", 4, "string", {}),
        ("wm", 5, "int64", {}),
    ])
    changed += _new_msg("EmbeddingDeltaEntry", [
        ("wm", 1, "int64", {}),
        ("ids", 2, "bytes", {}),
        ("rows", 3, "bytes", {}),
        ("dim", 4, "int32", {}),
        ("scale", 5, "float", {}),
        ("client_id", 6, "string", {}),
        ("seq", 7, "int64", {}),
    ])
    changed += _new_msg("EmbeddingFetchDeltaRequest", [
        ("table", 1, "string", {}),
        ("shard", 2, "int32", {}),
        ("since_wm", 3, "int64", {}),
    ])
    changed += _new_msg("EmbeddingFetchDeltaResponse", [
        # False = the bounded delta log no longer reaches back to
        # since_wm; the caller falls back to a full FetchShard copy
        ("found", 1, "bool", {}),
        ("wm", 2, "int64", {}),
        ("entries", 3, "", {
            "repeated": True,
            "type_name": ".elasticdl_tpu.EmbeddingDeltaEntry",
        }),
    ])
    changed += _new_msg("EmbeddingWatermarkRequest", [
        ("table", 1, "string", {}),
        ("shard", 2, "int32", {}),
        ("replica", 3, "bool", {}),
    ])
    changed += _new_msg("EmbeddingWatermarkResponse", [
        ("wm", 1, "int64", {}),
    ])

    # Wire-speed data plane (ISSUE 18). One fused request carries every
    # (table, shard) sub-pull a step routes to one owner: ids travel as
    # ONE flat int32 blob segmented by `counts`, rows come back as ONE
    # flat float32 blob segmented by counts x dims — both decoded as
    # numpy frombuffer views, no per-table pack/unpack. The response
    # piggybacks the owner's FULL primary watermark set (wm_tables /
    # wm_shards / wm_values triples) so steady-state freshness probes
    # stop being calls at all.
    changed += _new_msg("EmbeddingPullMultiRequest", [
        ("tables", 1, "string", {"repeated": True}),
        ("shards", 2, "int32", {"repeated": True}),
        ("counts", 3, "int32", {"repeated": True}),
        ("ids", 4, "bytes", {}),          # flat int32 LE, all sub-pulls
        ("map_version", 5, "int32", {}),
        ("replica", 6, "bool", {}),
    ])
    changed += _new_msg("EmbeddingPullMultiResponse", [
        ("rows", 1, "bytes", {}),         # flat float32 LE, all sub-pulls
        ("dims", 2, "int32", {"repeated": True}),
        ("wms", 3, "int64", {"repeated": True}),
        ("wm_tables", 4, "string", {"repeated": True}),
        ("wm_shards", 5, "int32", {"repeated": True}),
        ("wm_values", 6, "int64", {"repeated": True}),
    ])
    changed += _new_msg("EmbeddingWatermarkMultiRequest", [
        ("tables", 1, "string", {"repeated": True}),
        ("shards", 2, "int32", {"repeated": True}),
        ("replica", 3, "bool", {}),
    ])
    changed += _new_msg("EmbeddingWatermarkMultiResponse", [
        ("wms", 1, "int64", {"repeated": True}),
    ])
    # Streaming replica sync / shard migration: server-streamed chunks
    # under ONE call instead of unary call-per-chunk. The seq fence
    # (applied_json + wm for a shard copy, the target watermark for a
    # delta) travels in the FIRST frame; `last` closes the stream so a
    # mid-stream drop is distinguishable from completion.
    changed += _new_msg("EmbeddingShardChunk", [
        ("rows", 1, "bytes", {}),         # this frame's row slab
        ("offset", 2, "int32", {}),       # first row index of the slab
        ("rows_n", 3, "int32", {}),       # total rows (first frame)
        ("dim", 4, "int32", {}),          # first frame
        ("applied_json", 5, "string", {}),  # seq fence (first frame)
        ("wm", 6, "int64", {}),           # first frame
        ("last", 7, "bool", {}),
    ])
    changed += _new_msg("EmbeddingDeltaChunk", [
        ("found", 1, "bool", {}),         # first frame
        ("wm", 2, "int64", {}),           # target watermark (first frame)
        ("entries", 3, "", {
            "repeated": True,
            "type_name": ".elasticdl_tpu.EmbeddingDeltaEntry",
        }),
        ("last", 4, "bool", {}),
    ])
    # Same-host shared-memory short-circuit: the client asks the owner
    # to create a dedicated SPSC ring segment for this (client, owner)
    # pair; the owner answers with the segment name to attach. Any
    # failure (no shm on the box, segment gone, payload too big) falls
    # back to gRPC transparently.
    changed += _new_msg("EmbeddingShmNegotiateRequest", [
        ("client_host", 1, "string", {}),
        ("client_pid", 2, "int32", {}),
        ("slot_bytes", 3, "int32", {}),
    ])
    changed += _new_msg("EmbeddingShmNegotiateResponse", [
        ("ok", 1, "bool", {}),
        ("segment", 2, "string", {}),
        ("slot_bytes", 3, "int32", {}),
    ])
    return changed


def _offsets(fd: descriptor_pb2.FileDescriptorProto, data: bytes):
    """(name, start, end) for every top-level message/enum, byte offsets of
    each serialized sub-descriptor inside the file's serialized bytes —
    what protoc emits as ``_serialized_start/_end``."""
    out = []
    for enum in fd.enum_type:
        sub = enum.SerializeToString()
        start = data.find(sub)
        out.append(("_" + enum.name.upper(), start, start + len(sub)))
    for msg in fd.message_type:
        sub = msg.SerializeToString()
        start = data.find(sub)
        out.append(("_" + msg.name.upper(), start, start + len(sub)))
        for nested in msg.nested_type:
            nsub = nested.SerializeToString()
            nstart = data.find(nsub)
            out.append((
                "_" + msg.name.upper() + "_" + nested.name.upper(),
                nstart, nstart + len(nsub),
            ))
    return out


_TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated by elasticdl_tpu/proto/gen_pb2.py (no protoc on this image —
# the serialized descriptor is patched programmatically; schema source of
# truth: elasticdl_tpu.proto).  DO NOT EDIT BY HAND.
# source: elasticdl_tpu.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({serialized!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'elasticdl_tpu_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
  _JOBSTATUSRESPONSE_EVALMETRICSENTRY._options = None
  _JOBSTATUSRESPONSE_EVALMETRICSENTRY._serialized_options = b'8\\001'
{offset_lines}
# @@protoc_insertion_point(module_scope)
'''


def main() -> None:
    # read the CURRENT descriptor out of the checked-in pb2 without
    # importing it (importing would register it in the default pool and
    # block re-adding the patched file in this same process)
    with open(_PB2_PATH, encoding="utf-8") as f:
        src = f.read()
    marker = "AddSerializedFile("
    start = src.index(marker) + len(marker)
    # the literal sits on one line and may contain raw ')' bytes — take the
    # whole line and strip the closing paren of the call
    line = src[start:src.index("\n", start)]
    serialized = eval(line.rsplit(")", 1)[0])  # bytes literal from protoc

    fd = descriptor_pb2.FileDescriptorProto.FromString(serialized)
    changed = apply_patches(fd)
    data = fd.SerializeToString()

    lines = []
    for name, s, e in _offsets(fd, data):
        lines.append(f"  {name}._serialized_start={s}")
        lines.append(f"  {name}._serialized_end={e}")
    with open(_PB2_PATH, "w", encoding="utf-8") as f:
        f.write(_TEMPLATE.format(
            serialized=data, offset_lines="\n".join(lines) + "\n"))
    print(f"{_PB2_PATH}: {changed} schema addition(s), "
          f"{len(data)} descriptor bytes")


if __name__ == "__main__":
    main()
