"""Compatibility shims for older jax releases (gated, no-op on new jax).

The framework is written against the current ambient-mesh API surface:
`jax.set_mesh(mesh)` as a context manager, meshless `jax.shard_map(...)`
resolving the mesh from the ambient context, and
`jax.sharding.get_abstract_mesh()` to introspect it. Older jaxlib images
(e.g. 0.4.x, which this container bakes in) predate all three but carry
exact functional equivalents:

- `with mesh:` enters the thread-local resource env (the 0.4.x ambient
  mesh), which `jax._src.mesh.thread_resources` exposes during tracing;
- `jax.experimental.shard_map.shard_map` takes the mesh explicitly and
  spells partial-manual axes as the complementary `auto=` set instead of
  `axis_names=`.

`ensure()` installs adapters onto the `jax` module ONLY for attributes
that are missing, so on a current jax it does exactly nothing. Call it
from any module that uses these APIs, before first use (imports are cheap:
it runs once and latches).

This is a dependency gate, not a polyfill of semantics we don't use: the
adapters cover the call forms in this repo (context-managed set_mesh,
shard_map with in_specs/out_specs/axis_names, get_abstract_mesh for
axis_names/shape introspection) — not the full new-jax sharding-in-types
feature set.
"""

from __future__ import annotations

import contextlib

_done = False


def ensure() -> None:
    global _done
    if _done:
        return
    _done = True
    import jax

    if not hasattr(jax, "set_mesh"):
        jax.set_mesh = _set_mesh
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _get_ambient_mesh
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size
    if not hasattr(jax.lax, "pcast"):
        # varying-type casts only exist for the new replication checker;
        # with check_rep off (see _shard_map) the cast is a no-op
        jax.lax.pcast = _pcast_identity


@contextlib.contextmanager
def _set_mesh(mesh):
    """`with jax.set_mesh(mesh):` -> the 0.4.x thread-local resource env."""
    with mesh:
        yield mesh


def _get_ambient_mesh():
    """The mesh `with mesh:` made ambient (an empty Mesh outside any).
    Callers in this repo only read `.axis_names` / `.shape`, which the
    physical Mesh serves identically to the new AbstractMesh."""
    from jax._src.mesh import thread_resources

    return thread_resources.env.physical_mesh


def _axis_size(axis_name):
    """Static size of a named mesh axis inside shard_map tracing. 0.4.x
    keeps it in core's axis env (axis_frame returns the bare int there)."""
    from jax._src.core import axis_frame

    frame = axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def _pcast_identity(x, axes=(), *, to=None):
    return x


def _shard_map(f, mesh=None, in_specs=None, out_specs=None, axis_names=None,
               **kwargs):
    """Meshless `jax.shard_map(f, in_specs=..., out_specs=...,
    axis_names=...)` on top of the experimental API: mesh from the ambient
    context, `axis_names` (manual axes) mapped to its complement `auto`.
    check_rep defaults off — the 0.4.x replication checker predates some
    collectives these kernels use."""
    from jax.experimental.shard_map import shard_map as _sm

    if mesh is None:
        mesh = _get_ambient_mesh()
        if not mesh.axis_names:
            raise ValueError(
                "jax.shard_map compat: no mesh argument and no ambient mesh "
                "(enter `with jax.set_mesh(mesh):` first)"
            )
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    kwargs.setdefault("check_rep", False)
    if in_specs is None or out_specs is None:
        raise TypeError("shard_map compat requires in_specs and out_specs")
    return _sm(f, mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
