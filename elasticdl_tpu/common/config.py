"""Typed job configuration with argv round-trip.

Reference parity: elasticdl/python/common/args.py. The reference's config plane
works by parsing argparse flags in the client, then *re-serializing the parsed
namespace back into argv* for the master pod's command line, which does the same
for workers. That propagation trick is simple and debuggable, so we keep it —
but as one typed dataclass (`JobConfig`) with `to_argv()` / `from_argv()`
instead of hand-maintained parallel argparse groups.

Roles (client / master / worker) share this single schema; each reads the
fields it needs. Freeform `--model_params` / `--data_reader_params` key=value
strings pass user parameters through to model-zoo code, matching the
reference's behavior.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional

from elasticdl_tpu.common.constants import DEFAULT_MASTER_PORT, JobType


def parse_kv_params(s: str) -> Dict[str, Any]:
    """Parse 'a=1;b=hello;c=0.5' into a dict with literal-ish coercion.

    Reference parity: the reference's `--model_params` / `--envs` freeform
    key=value passthrough (elasticdl/python/common/args.py).
    """
    out: Dict[str, Any] = {}
    if not s:
        return out
    for item in s.split(";"):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"Malformed key=value item: {item!r}")
        k, v = item.split("=", 1)
        k, v = k.strip(), v.strip()
        for caster in (int, float):
            try:
                out[k] = caster(v)
                break
            except ValueError:
                continue
        else:
            if v.lower() in ("true", "false"):
                out[k] = v.lower() == "true"
            else:
                out[k] = v
    return out


def format_kv_params(d: Dict[str, Any]) -> str:
    return ";".join(f"{k}={v}" for k, v in d.items())


# Valid --remat_policy names. The jax.checkpoint policies they map to live
# in training/trainer.resolve_remat_policy (kept out of this module so the
# client submit path stays framework-free); tests pin the two in sync.
REMAT_POLICY_NAMES = ("dots", "dots_no_batch", "nothing")


@dataclass
class JobConfig:
    """Everything a training/evaluation/prediction job needs, in one place."""

    # --- identity ---
    job_name: str = "edl-job"
    job_type: str = JobType.TRAINING_WITH_EVALUATION

    # --- model-zoo contract (reference: --model_zoo / --model_def) ---
    model_zoo: str = "model_zoo"
    model_def: str = ""           # dotted path: "mnist.mnist_cnn.custom_model"
    model_params: Dict[str, Any] = field(default_factory=dict)
    # Optional per-function overrides (reference: --loss=..., --optimizer=...)
    loss: str = ""
    optimizer: str = ""
    dataset_fn: str = ""
    eval_metrics_fn: str = ""
    prediction_outputs_processor: str = ""

    # --- data ---
    training_data: str = ""
    validation_data: str = ""
    prediction_data: str = ""
    data_reader: str = ""          # "" = infer from path; "recordio"|"csv"|...
    data_reader_params: Dict[str, Any] = field(default_factory=dict)
    records_per_task: int = 4096
    num_epochs: int = 1
    minibatch_size: int = 64
    shuffle: bool = True
    shuffle_seed: int = 0

    # --- evaluation (units: model-version steps = minibatches, matching the
    # reference's --evaluation_steps; 0 = evaluate at epoch end only) ---
    evaluation_steps: int = 0
    evaluation_start_delay_steps: int = 0

    # --- checkpointing (reference: --checkpoint_steps etc.) ---
    checkpoint_dir: str = ""
    checkpoint_steps: int = 0
    keep_checkpoint_max: int = 3
    output: str = ""               # final model export dir
    summary_dir: str = ""          # JSONL + TensorBoard summaries (master-side)
    # Elastic linear LR scaling: on membership change, scale the (injected)
    # learning rate by alive_workers/num_workers (see training/lr_modulation)
    scale_lr_with_workers: bool = False
    # >1: workers run K train steps per XLA dispatch (Trainer.train_many,
    # lax.scan over a stacked batch group) — amortizes host->device dispatch
    # latency; loss/step-time telemetry becomes per-group, preemption checks
    # happen at group boundaries.
    steps_per_dispatch: int = 1
    # Async host->device batch prefetch depth (0 disables; see data/prefetch)
    prefetch_batches: int = 2
    # Wire dtype for float batch features ("" = native, "bfloat16" halves
    # transfer bytes; lossless for bf16-compute models — see data/prefetch)
    wire_dtype: str = ""

    # --- profiling (SURVEY §5 tracing; the reference had no in-repo tracer,
    # jax.profiler makes this nearly free) ---
    profile_dir: str = ""          # "" = off; else jax.profiler trace output
    profile_start_step: int = 5    # skip compile + warmup steps
    profile_steps: int = 20        # trace this many steps, then stop

    # --- observability (metrics registry + trace spans; observability/) ---
    # /metrics + /healthz HTTP endpoint per process: 0 = ephemeral port
    # (default), -1 disables; the EDL_METRICS_PORT env overrides either.
    metrics_port: int = 0
    # control-plane trace spans (trace.jsonl, one file per role under
    # <trace_dir>/<role>/): "" derives <summary_dir>/trace when summary_dir
    # is set (spans stay in-memory otherwise); "off" disables the file sink.
    trace_dir: str = ""
    # Incident flight recorder (observability/flight.py): where per-process
    # flight-<role>-<pid>.json bundles land on crash, SIGUSR2, the
    # /debug/flight endpoint, or straggler-hook escalation. "" derives
    # <summary_dir|checkpoint_dir>/flight
    # (memory-only when neither is set); "off" disables dumping (the ring
    # still records); EDL_FLIGHT_DIR overrides either way.
    flight_dir: str = ""
    # Flight ring capacity (records kept at full fidelity per process).
    flight_ring: int = 4096
    # Metrics time series (observability/timeseries.py): every process
    # keeps a bounded ring of periodic registry snapshots, served by
    # GET /timeseries and persisted as a rolling metrics_history.jsonl
    # under <summary_dir|checkpoint_dir>/timeseries/<role>/. The master's
    # ring additionally carries fleet series computed from heartbeat
    # stats payloads — the alert engine's input.
    timeseries_interval_s: float = 5.0
    timeseries_samples: int = 720      # ring capacity: 720 x 5s = 1h
    # Declarative alert rules (observability/alerts.py), evaluated on the
    # master's wait poll: "" = the shipped default rule set (straggler,
    # backlog-per-worker, data_wait-dominant fleet, embedding pull p99,
    # shard imbalance), "off" = disabled, else a path to a JSON list of
    # rule objects (see docs/observability.md "Alert rules").
    alert_rules: str = ""
    # Straggler-scorer quorum (observability/health.py ClusterHealth):
    # minimum workers with fresh telemetry before robust-z scoring runs.
    # Floor 2 — a 2-worker fleet can still flag a straggler through the
    # min_ratio gate; below that "who is slow" is undecidable. The old
    # hard-coded 3 is the default.
    straggler_quorum: int = 3

    # --- closed-loop autoscaler (master/autoscaler.py; ROADMAP 3) ---
    # false (default) = every rescale stays human-initiated (the
    # pre-autoscaler behavior; also the way to DISABLE the loop). true =
    # the master turns health signals into journaled, fenced rescale
    # actions: evict confirmed stragglers (drain-first), grow on
    # sustained dispatcher backlog, shrink when data_wait dominates.
    autoscale: bool = False
    # world bounds the policy may move within (max 0 = unbounded)
    autoscale_min_workers: int = 1
    autoscale_max_workers: int = 0
    # minimum seconds between APPLIED actions (anti-flap; inherited
    # across master restarts via the journal's autoscale records)
    autoscale_cooldown_s: float = 120.0
    # hysteresis: a signal must persist this long before it is acted on
    autoscale_hold_s: float = 30.0
    # per-job action budget — the blast-radius cap; once spent, every
    # further decision suppresses with `budget_exhausted`
    autoscale_actions_max: int = 8
    # cost-model seed: projected per-worker rescale cost in seconds.
    # Seed it from YOUR deployment's measured `bench.py rescale`
    # `time_to_recovery_s` (bench-baselines/bench-rescale.json); the
    # model then updates online from observed re-formation durations.
    autoscale_rescale_cost_s: float = 10.0
    # horizon the projected goodput gain accrues over: an action is
    # taken only when gain(horizon) > rescale_cost x world
    autoscale_horizon_s: float = 300.0
    # signal damping in [0, 1): EWMA smoothing factor applied to the
    # grow/shrink alert values — a decision needs the SMOOTHED value
    # past the rule threshold by a deadband margin, so one noisy sample
    # cannot thrash the loop. 0 (default) = decide on raw signals.
    autoscale_damping: float = 0.0
    # anti-thrash reversal hold: a grow→shrink (or shrink→grow)
    # candidate within this many seconds of the last applied opposite
    # action suppresses with reason `reversal_hold`. 0 = off.
    autoscale_reversal_hold_s: float = 0.0

    # --- closed-loop LAYOUT controller (master/layout_controller.py;
    # ISSUE 20 — the embedding-tier sibling of the autoscaler above) ---
    # false (default) = the embedding layout stays human-operated; true
    # = skew signals (shard imbalance, cache-hit collapse, the sketch's
    # hot-id share) drive journaled, cost-gated layout actions: per-
    # shard replica fan-out, shard split/merge through the two-phase
    # reshard fence, and hot-id promotion into a worker-replicated set.
    layout_autoscale: bool = False
    # shard-count bounds for split/merge. max 0 = splitting DISABLED
    # (replica fan-out and hot-id actions still run); merge never folds
    # below the bootstrap shard count regardless of min.
    layout_max_shards: int = 0
    layout_min_shards: int = 1
    # per-shard read-replica cap for replica_fanout
    layout_max_replicas: int = 2
    # ultra-hot set size (worker-replicated sketch head); 0 disables
    # hot promotion
    layout_hot_k: int = 16
    # PER-KIND cooldown between applied actions of the same kind (a
    # replica fan-out must not cool down a pending split); inherited
    # across master restarts via the journal's `layout` records
    layout_cooldown_s: float = 60.0
    # hysteresis: a skew signal must persist this long before action
    layout_hold_s: float = 15.0
    # per-job layout action budget (blast-radius cap)
    layout_actions_max: int = 16
    # cost-model seed: projected blocked-read-seconds per shard touched
    # by a migration. Seed it from YOUR deployment's measured `bench.py
    # embedding_tier` reshard `recovery_s` (bench-baselines/
    # bench-embedding-tier.json); EWMA-updated from real migrations.
    layout_migrate_cost_s: float = 0.16
    # horizon the projected read-stall relief accrues over
    layout_horizon_s: float = 120.0

    # --- cluster shape / elasticity ---
    # Who owns worker lifecycles: "" = the launcher (local subprocess
    # manager, or the k8s StatefulSet's own self-healing); "k8s" = the MASTER
    # creates/watches/relaunches worker pods through the k8s API — the
    # reference's k8s_instance_manager flavor (master/k8s_instance_manager.py)
    instance_manager: str = ""
    num_workers: int = 1
    # >1 = multi-process SPMD cohort: one jax.distributed world + one global
    # mesh across this many processes (worker/cohort.py). The master sees one
    # logical worker (the cohort leader).
    num_processes: int = 1
    num_minibatches_per_task: int = 0   # 0 = derive from records_per_task
    max_task_retries: int = 3
    relaunch_max: int = 3               # reference: --relaunch_pod_max_num
    task_timeout_s: float = 600.0
    worker_heartbeat_s: float = 10.0
    # No successful master RPC for this long -> the worker assumes the
    # master is permanently gone and exits EX_TEMPFAIL (a live instance
    # manager relaunches it; a truly orphaned worker frees its resources
    # instead of spinning on a dead address forever). 0 disables.
    master_unreachable_timeout_s: float = 300.0
    # Persistent XLA compilation cache (common/runtime.py): relaunched
    # workers deserialize the previous generation's executables instead of
    # paying the 20-40 s TPU recompile on every elastic recovery. Point it
    # at storage shared across relaunches (e.g. next to checkpoint_dir).
    compilation_cache_dir: str = ""
    # <0 keeps JAX's default floor (~1 s: only expensive programs persist);
    # >=0 overrides it (tests use 0 so test-sized programs cache too).
    compilation_cache_min_compile_s: float = -1.0
    # Rescale fast path: once steady state is reached, precompile the step
    # programs for neighbor world sizes (N±1, plus any size announced by
    # the master's pending-membership signal) in a background thread so a
    # resize lands on a warm executable cache (training/compile_cache.py).
    speculative_compile: bool = False
    # Chaos (local launcher): survive up to this many in-process master
    # crashes — the `master_crash` fault site's `drop` action raised out of
    # Master.wait is caught by client/local.py, which crashes the master
    # abruptly and rebuilds it on the same port; the successor replays the
    # control-plane journal (requires checkpoint_dir) and workers reconnect
    # under the bumped generation without restarting. 0 = a master crash
    # fails the job (the pre-journal behavior).
    master_restarts: int = 0
    # fsync every control-journal commit (the crash-durability contract:
    # a transition is on disk before its effect is observable). Task
    # lease/report commits happen under the dispatcher lock, so on a
    # high-latency checkpoint filesystem (NFS / GCS FUSE) per-commit
    # fsync bounds master dispatch throughput to ~1/fsync-latency
    # fleet-wide. false trades the last-commit durability window (a crash
    # may lose transitions still in the page cache; workers then redo the
    # affected tasks — at-least-once, never silent loss) for throughput.
    journal_fsync: bool = True
    # Journal group-commit window (ms). 0 = per-commit mode (the
    # journal_fsync tradeoff above in full). >0 = mutators enqueue onto an
    # ordered commit queue and a committer thread flushes the whole window
    # under ONE write+fsync; RPC responses that acknowledge a journaled
    # transition are released only after their commit's fsync lands
    # (ack-after-fsync), so durability is NOT weakened — per-request fsync
    # cost is amortized across every commit in the window instead. See
    # docs/performance.md "Control-plane throughput".
    journal_group_commit_ms: float = 0.0
    # Batched task leases: workers ask for up to this many tasks per
    # GetTask round-trip (one group-committed journal batch) and drain the
    # local lease queue before re-polling. 1 = classic one-lease-per-poll.
    # Sizing caveat: the master's task_timeout_s clock starts at LEASE
    # time for every task in the batch — keep batch * per-task wall time
    # well under task_timeout_s or tail leases expire while queued.
    task_lease_batch: int = 1

    # --- elastic sharded embedding tier (elasticdl_tpu/embedding/) ---
    # >0 enables the tier: embedding tables declared by the model are
    # id-sharded (`shard = id % embedding_shards`) across owning workers,
    # pulled/pushed per batch (deduped, per-shard batched), with the
    # shard map owned by the master and journaled (survives master
    # crash-restart); shards migrate on world change. Size it at 1-4x the
    # expected worker count — see docs/performance.md "Embedding tier
    # sizing". 0 = off (tables live in HBM inside the jitted step, the
    # default single-host path).
    embedding_shards: int = 0
    # --- serving-grade embedding reads (ISSUE 13), three switchable
    # layers on top of the tier (each independently attributable in
    # `bench.py embedding_tier`):
    # worker-local hot-row cache capacity in ROWS PER TABLE (0 = off).
    # Size from the measured hot set: `hot_id_share` in tier_stats()
    # says what fraction of pull traffic the sketch's top-K ids carry —
    # see docs/performance.md "Embedding read path".
    embedding_cache_rows: int = 0
    # staleness bound in PUSH-WATERMARK units (shard pushes, not
    # seconds): a cached row / replica answer more than this many
    # applied pushes behind the observed owner watermark is refetched.
    # 0 = always revalidate against the owner's watermark; larger
    # trades convergence freshness for hit rate.
    embedding_cache_staleness: int = 1
    # read replicas per shard (0 = off): the master assigns and
    # journal-commits replica owners next to primaries; replicas sync
    # by watermark-tagged deltas, reads fan out to the least-loaded
    # fresh-enough copy, writes stay primary-only, and a dead owner's
    # shard promotes a surviving replica.
    embedding_read_replicas: int = 0
    # pull pipeline lookahead (0 = off): overlap the NEXT batch's
    # deduped pull with the current step's compute; drained (batches
    # re-issued) across rescale/reshard.
    embedding_pull_pipeline: int = 0
    # --- partition-tolerant gRPC data plane (ISSUE 15,
    # embedding/data_plane.py) ---
    # "local" = the in-process LocalTransport (single-process jobs, the
    # thread-cohort bench swarm); "grpc" = each worker serves its owning
    # shards over a per-worker EmbeddingData endpoint (bound next to the
    # observability endpoint, address ridden on RegisterWorker and the
    # shard-map response) and routes peers' shards through
    # GrpcTransport, wrapped in the ResilientTransport robustness layer
    # (deadlines, per-owner breakers, hedged reads, degraded-mode
    # serving, queued pushes).
    embedding_transport: str = "local"
    # per-call deadline BUDGET for data-plane pulls/pushes, in ms:
    # retries and backoff sleeps spend it, each attempt's wire deadline
    # is the remainder split over remaining attempts, and it propagates
    # to the owner as the gRPC deadline (EDL208 polices stub calls that
    # skip it).
    embedding_rpc_deadline_ms: int = 2000
    # hedge delay for data-plane reads, in ms: a pull whose primary has
    # not answered after this long races a replica (first credible
    # answer wins). 0 = derive from the measured pull p99 (x1.5, 1 ms
    # floor) — see docs/performance.md "Hedge-delay sizing"; < 0
    # disables hedging.
    embedding_hedge_ms: int = 0
    # bounded push queue behind an open owner breaker (entries; 0 =
    # never queue — pushes block/raise through the partition instead).
    # Queued pushes journal to <checkpoint_dir>/emb-push-queue.jsonl
    # and drain in order on reconnect under their original seqs.
    embedding_push_queue: int = 1024
    # same-host shared-memory short-circuit (ISSUE 18): when a tier
    # client and an owning store share a host, hot data-plane calls
    # ride a negotiated shared-memory ring instead of the gRPC
    # loopback (~10x lower per-call cost); any ring failure falls
    # back to gRPC transparently. grpc transport only; off = always
    # use the socket.
    embedding_shm: bool = True

    # --- mesh / parallelism (TPU-native; no reference analog) ---
    mesh_shape: str = ""           # "" = all devices on axis "data"; "4,2" = data=4, model=2
    # Multi-slice: per-axis DCN (across-slice) factors, named form only
    # ("data=2" = data-parallel across 2 slices). mesh_shape then describes
    # ONE slice's ICI layout; see parallel/mesh.build_hybrid_mesh.
    dcn_mesh_shape: str = ""
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    remat: bool = False            # jax.checkpoint the forward pass
    # named jax.checkpoint policy (implies remat): "dots" keeps MXU outputs
    # and recomputes elementwise, "dots_no_batch" also drops attention
    # scores, "nothing" recomputes everything (min HBM). "" = full remat
    # when --remat is set. See training/trainer.resolve_remat_policy.
    remat_policy: str = ""
    # Gradient accumulation: split each minibatch into K micro-batches and
    # scan forward+backward holding one micro-batch of activations live —
    # with a per-example (vector) loss, grads are EXACTLY the full-batch
    # step's (masked-weighted), so K is a pure HBM knob for raising
    # effective batch size. A loss returning a pre-reduced SCALAR weighs
    # micro-batches equally instead (trainer warns once). Must divide
    # minibatch_size.
    grad_accum_steps: int = 1

    # --- addresses / runtime ---
    master_addr: str = f"localhost:{DEFAULT_MASTER_PORT}"
    coordinator_addr: str = ""     # jax.distributed coordination service
    use_tpu: bool = True
    log_level: str = "INFO"

    # --- k8s submission (client-side; reference: --image_name etc.) ---
    image_name: str = ""
    namespace: str = "default"
    master_resource_request: str = "cpu=1,memory=2048Mi"
    worker_resource_request: str = "cpu=4,memory=8192Mi"
    tpu_type: str = ""             # e.g. "v5e-32"
    volume: str = ""
    image_pull_policy: str = "IfNotPresent"
    restart_policy: str = "Never"
    envs: Dict[str, Any] = field(default_factory=dict)

    # ------------------------------------------------------------------ #

    def validate(self) -> None:
        if not self.model_def:
            raise ValueError("model_def is required (e.g. mnist.mnist_cnn.custom_model)")
        if self.remat_policy and self.remat_policy not in REMAT_POLICY_NAMES:
            # fail at submit time, not after TPUs are allocated and the
            # first train step builds — against the plain name set, NOT by
            # importing training.trainer (which pulls jax/optax/flax into
            # the framework-free client submit path). trainer's
            # resolve_remat_policy does the jax lookup at construction;
            # a test pins the two lists together.
            raise ValueError(
                f"unknown remat policy {self.remat_policy!r}; choose from "
                f"{sorted(REMAT_POLICY_NAMES)} or '' for full remat"
            )
        if self.grad_accum_steps < 1:
            raise ValueError("grad_accum_steps must be >= 1")
        if self.master_restarts < 0:
            raise ValueError("master_restarts must be >= 0")
        if self.journal_group_commit_ms < 0:
            raise ValueError("journal_group_commit_ms must be >= 0 (0 = "
                             "per-commit fsync)")
        if self.journal_group_commit_ms > 10_000:
            # Commit.wait gives a flush 30s before declaring the journal
            # wedged; a window at (or past) that order would fail every
            # journaled RPC before its batch could ever flush. 10s is
            # already far beyond any sane fsync latency it could amortize.
            raise ValueError(
                "journal_group_commit_ms must be <= 10000 (the window is "
                "latency every journaled ack pays; size it near your "
                "fsync latency — see docs/performance.md)"
            )
        if self.task_lease_batch < 1:
            raise ValueError("task_lease_batch must be >= 1")
        if self.embedding_shards < 0:
            raise ValueError("embedding_shards must be >= 0 (0 = tier off)")
        if self.embedding_cache_rows < 0:
            raise ValueError(
                "embedding_cache_rows must be >= 0 (0 = cache off)")
        if self.embedding_cache_staleness < 0:
            raise ValueError(
                "embedding_cache_staleness must be >= 0 (watermark "
                "units: pushes a cached row may lag the owner)")
        if self.embedding_read_replicas < 0:
            raise ValueError(
                "embedding_read_replicas must be >= 0 (0 = no replicas)")
        if self.embedding_pull_pipeline < 0:
            raise ValueError(
                "embedding_pull_pipeline must be >= 0 (0 = blocking "
                "pulls)")
        if (self.embedding_read_replicas > 0
                and self.embedding_shards <= 0):
            raise ValueError(
                "embedding_read_replicas requires the tier "
                "(embedding_shards > 0)")
        if self.embedding_transport not in ("local", "grpc"):
            raise ValueError(
                "embedding_transport must be 'local' or 'grpc' "
                f"(got {self.embedding_transport!r})")
        if (self.embedding_transport == "grpc"
                and self.embedding_shards <= 0):
            raise ValueError(
                "embedding_transport='grpc' requires the tier "
                "(embedding_shards > 0)")
        if self.embedding_rpc_deadline_ms <= 0:
            # a deadline-less data plane blocks forever against a
            # half-dead owner — the exact failure EDL208 polices in code
            raise ValueError(
                "embedding_rpc_deadline_ms must be > 0 (the per-call "
                "deadline budget; there is no 'no deadline' mode)")
        if self.embedding_push_queue < 0:
            raise ValueError(
                "embedding_push_queue must be >= 0 (0 = never queue "
                "behind a partitioned owner)")
        if self.flight_ring < 16:
            # a ring too small to hold even one incident's records would
            # silently produce useless bundles; fail at submit time
            raise ValueError("flight_ring must be >= 16 records")
        if self.timeseries_interval_s <= 0:
            raise ValueError("timeseries_interval_s must be > 0")
        if self.timeseries_samples < 8:
            # a ring shorter than any alert window is a rule engine
            # evaluating over nothing; fail at submit time
            raise ValueError("timeseries_samples must be >= 8")
        if self.straggler_quorum < 2:
            # with 1 reporter the median IS the reporter and scoring is
            # vacuous; 2 works through the min_ratio gate (the satellite
            # unlock for 2-worker fleets)
            raise ValueError("straggler_quorum must be >= 2")
        if self.autoscale:
            if self.autoscale_min_workers < 1:
                raise ValueError("autoscale_min_workers must be >= 1")
            if (self.autoscale_max_workers
                    and self.autoscale_max_workers
                    < self.autoscale_min_workers):
                raise ValueError(
                    "autoscale_max_workers must be 0 (unbounded) or >= "
                    "autoscale_min_workers")
            if self.autoscale_cooldown_s < 0:
                raise ValueError("autoscale_cooldown_s must be >= 0")
            if self.autoscale_hold_s < 0:
                raise ValueError("autoscale_hold_s must be >= 0")
            if self.autoscale_actions_max < 1:
                raise ValueError(
                    "autoscale_actions_max must be >= 1 (use "
                    "--autoscale false to disable the loop)")
            if self.autoscale_rescale_cost_s <= 0:
                raise ValueError(
                    "autoscale_rescale_cost_s must be > 0 (seed it from "
                    "bench.py rescale's time_to_recovery_s)")
            if self.autoscale_horizon_s <= 0:
                raise ValueError("autoscale_horizon_s must be > 0")
            if not 0.0 <= self.autoscale_damping < 1.0:
                raise ValueError(
                    "autoscale_damping must be in [0, 1): it is the EWMA "
                    "smoothing factor (0 = no damping); 1 would freeze "
                    "the smoothed signal forever")
            if self.autoscale_reversal_hold_s < 0:
                raise ValueError(
                    "autoscale_reversal_hold_s must be >= 0 (0 = off)")
            if not self.checkpoint_dir:
                # decisions are journaled and replayed at takeover; a
                # journal-less autoscaler would re-fire after every
                # master restart — the same reason master_restarts
                # requires a checkpoint_dir
                raise ValueError(
                    "autoscale requires checkpoint_dir: decisions are "
                    "journaled under <checkpoint_dir>/control/ and "
                    "replayed at master takeover"
                )
        if self.layout_autoscale:
            if self.layout_max_shards < 0:
                raise ValueError(
                    "layout_max_shards must be >= 0 (0 disables splits)")
            if self.layout_min_shards < 1:
                raise ValueError("layout_min_shards must be >= 1")
            if (self.layout_max_shards
                    and self.layout_max_shards < self.layout_min_shards):
                raise ValueError(
                    "layout_max_shards must be 0 (splits disabled) or >= "
                    "layout_min_shards")
            if self.layout_max_replicas < 0:
                raise ValueError("layout_max_replicas must be >= 0")
            if self.layout_hot_k < 0:
                raise ValueError(
                    "layout_hot_k must be >= 0 (0 disables hot promotion)")
            if self.layout_cooldown_s < 0:
                raise ValueError("layout_cooldown_s must be >= 0")
            if self.layout_hold_s < 0:
                raise ValueError("layout_hold_s must be >= 0")
            if self.layout_actions_max < 1:
                raise ValueError(
                    "layout_actions_max must be >= 1 (use "
                    "--layout_autoscale false to disable the loop)")
            if self.layout_migrate_cost_s <= 0:
                raise ValueError(
                    "layout_migrate_cost_s must be > 0 (seed it from the "
                    "bench embedding_tier reshard recovery_s)")
            if self.layout_horizon_s <= 0:
                raise ValueError("layout_horizon_s must be > 0")
            if not self.checkpoint_dir:
                # same contract as autoscale: decisions are journaled
                # `layout` records replayed at master takeover; without
                # a journal a restarted master would re-fire them
                raise ValueError(
                    "layout_autoscale requires checkpoint_dir: layout "
                    "decisions are journaled under <checkpoint_dir>/"
                    "control/ and replayed at master takeover"
                )
        if self.master_restarts > 0 and not self.checkpoint_dir:
            # a journal-less successor rebuilds the dispatcher from scratch
            # — every already-finished task would be recreated and re-run,
            # silently breaking exactly-once accounting; fail at submit time
            raise ValueError(
                "master_restarts requires checkpoint_dir: master recovery "
                "replays the control-plane journal under "
                "<checkpoint_dir>/control/"
            )
        if self.grad_accum_steps > 1 and (
            self.minibatch_size % self.grad_accum_steps
        ):
            raise ValueError(
                f"grad_accum_steps ({self.grad_accum_steps}) must divide "
                f"minibatch_size ({self.minibatch_size})"
            )
        if self.minibatch_size <= 0:
            raise ValueError("minibatch_size must be positive")
        if self.num_workers <= 0:
            raise ValueError("num_workers must be positive")
        if self.num_processes <= 0:
            raise ValueError("num_processes must be positive")
        if self.instance_manager not in ("", "k8s"):
            raise ValueError(
                f"instance_manager must be '' or 'k8s', got "
                f"{self.instance_manager!r}"
            )
        if self.instance_manager == "k8s" and self.num_processes > 1:
            # the master-managed-pod flavor has no per-pod cohort addressing
            # (coordinator DNS + stable process ids) — without this guard the
            # worker pod's jax.distributed init waits forever for peers that
            # were never created
            raise ValueError(
                "instance_manager='k8s' manages plain worker pods and cannot "
                "form an SPMD cohort; for num_processes>1 use the default "
                "StatefulSet flavor (stable ordinals + headless service)"
            )
        if self.instance_manager == "k8s" and self.tpu_type:
            from elasticdl_tpu.common.constants import TPU_TYPES

            hosts = TPU_TYPES.get(self.tpu_type, (None, None, 1, None))[2]
            if hosts > 1:
                # statically knowable at submit time — failing here beats the
                # master discovering it pod-by-pod minutes later in-cluster
                raise ValueError(
                    f"tpu_type={self.tpu_type} is a {hosts}-host slice (one "
                    "SPMD cohort); instance_manager='k8s' manages plain "
                    "single-host pods — use the default StatefulSet flavor"
                )
        is_training = self.job_type in (
            JobType.TRAINING_ONLY, JobType.TRAINING_WITH_EVALUATION
        )
        if is_training and self.num_workers > 1:
            # N independent worker processes would each hold their own model
            # replica with NO gradient exchange (and only worker 0 would
            # checkpoint) — silently-divergent training. The reference's
            # semantic is one shared model across workers (SURVEY §3.3);
            # here that is the SPMD cohort: one jax.distributed world of
            # `num_processes` processes behind a single logical worker.
            raise ValueError(
                f"num_workers={self.num_workers} with a training job would "
                "train num_workers INDEPENDENT model replicas (gradients are "
                "never exchanged between plain workers). For data-parallel "
                f"training use the SPMD cohort: num_processes="
                f"{self.num_workers} (and num_workers=1). Plain "
                "num_workers>1 is only valid for evaluation_only / "
                "prediction_only jobs, whose tasks are embarrassingly "
                "parallel."
            )

    # --- argv round-trip ------------------------------------------------ #

    _DICT_FIELDS = ("model_params", "data_reader_params", "envs")

    def to_argv(self) -> List[str]:
        """Serialize to a flat argv, skipping fields at their default value."""
        argv: List[str] = []
        defaults = JobConfig()
        for f in fields(self):
            v = getattr(self, f.name)
            if v == getattr(defaults, f.name):
                continue
            flag = "--" + f.name
            if f.name in self._DICT_FIELDS:
                argv += [flag, format_kv_params(v)]
            elif isinstance(v, bool):
                argv += [flag, "true" if v else "false"]
            else:
                argv += [flag, str(v)]
        return argv

    @classmethod
    def from_argv(cls, argv: List[str]) -> "JobConfig":
        parser = cls.build_parser()
        ns, unknown = parser.parse_known_args(argv)
        if unknown:
            raise ValueError(f"Unknown flags: {unknown}")
        return cls.from_namespace(ns)

    @classmethod
    def build_parser(cls, parser: Optional[argparse.ArgumentParser] = None) -> argparse.ArgumentParser:
        parser = parser or argparse.ArgumentParser("elasticdl-tpu")
        defaults = cls()
        for f in fields(cls):
            flag = "--" + f.name
            default = getattr(defaults, f.name)
            if f.name in cls._DICT_FIELDS:
                parser.add_argument(flag, type=str, default=format_kv_params(default))
            elif isinstance(default, bool):
                parser.add_argument(
                    flag, type=lambda s: s.lower() in ("true", "1", "yes"), default=default
                )
            else:
                parser.add_argument(flag, type=type(default), default=default)
        return parser

    @classmethod
    def from_namespace(cls, ns: argparse.Namespace) -> "JobConfig":
        kwargs: Dict[str, Any] = {}
        for f in fields(cls):
            v = getattr(ns, f.name)
            if f.name in cls._DICT_FIELDS and isinstance(v, str):
                v = parse_kv_params(v)
            kwargs[f.name] = v
        cfg = cls(**kwargs)
        return cfg

    def replace(self, **kw: Any) -> "JobConfig":
        return dataclasses.replace(self, **kw)

    def dcn_axes_sizes(self) -> Dict[str, int]:
        """Parse `dcn_mesh_shape` (named form only; {} when unset)."""
        if not self.dcn_mesh_shape:
            return {}
        if "=" not in self.dcn_mesh_shape:
            raise ValueError(
                f"dcn_mesh_shape must use the named form 'data=2', got "
                f"{self.dcn_mesh_shape!r}"
            )
        sizes: Dict[str, int] = {}
        for part in self.dcn_mesh_shape.split(","):
            name, _, size = part.partition("=")
            name = name.strip()
            if not name or not size.strip().isdigit() or int(size) < 1:
                raise ValueError(
                    f"dcn_mesh_shape entry {part!r} is not name=positive-size "
                    f"(got dcn_mesh_shape={self.dcn_mesh_shape!r})"
                )
            if name in sizes:
                raise ValueError(
                    f"dcn_mesh_shape names axis {name!r} twice: "
                    f"{self.dcn_mesh_shape!r}"
                )
            sizes[name] = int(size)
        return sizes

    def mesh_axes_sizes(self, n_devices: int) -> Dict[str, int]:
        """Resolve `mesh_shape` against an actual device count.

        Two forms: positional "4" / "4,2" (data[, model], back-compat) and
        named "data=2,seq=4" / "data=4,model=2" — named supports any axis
        set (data/model/seq/pp/expert) in mesh order, so a job can request
        the sequence-, tensor-, pipeline-, or expert-parallel meshes the
        zoo transformer consumes.
        """
        if not self.mesh_shape:
            return {"data": n_devices}
        if "=" in self.mesh_shape:
            sizes: Dict[str, int] = {}
            for part in self.mesh_shape.split(","):
                name, _, size = part.partition("=")
                name = name.strip()
                if not name or not size.strip().isdigit():
                    raise ValueError(
                        f"mesh_shape entry {part!r} is not name=size "
                        f"(got mesh_shape={self.mesh_shape!r})"
                    )
                if name in sizes:
                    raise ValueError(
                        f"mesh_shape names axis {name!r} twice: {self.mesh_shape!r}"
                    )
                sizes[name] = int(size)
        else:
            parts = [int(p) for p in self.mesh_shape.split(",")]
            if len(parts) == 1:
                sizes = {"data": parts[0]}
            elif len(parts) == 2:
                sizes = {"data": parts[0], "model": parts[1]}
            else:
                raise ValueError(
                    f"positional mesh_shape must have 1 or 2 dims, got "
                    f"{self.mesh_shape!r}; use named form 'data=4,seq=2'"
                )
        total = 1
        for s in sizes.values():
            total *= s
        if total != n_devices:
            raise ValueError(
                f"mesh_shape {self.mesh_shape!r} needs {total} devices, have {n_devices}"
            )
        return sizes
