"""Seeded, deterministic fault injection for the elastic control plane.

The paper's elasticity claims (lease recovery, membership reaping, cohort
re-formation, checkpoint restore) are only as good as the fault schedules
they are tested under (ElasWave, arxiv 2510.00606; the multi-tenant
elastic-DL study, arxiv 1909.11985). This module lets tests and operators
*produce* those schedules on demand, reproducibly: a schedule spec plus a
seed fully determines, for every named injection point, exactly which hits
fire which faults.

Schedule spec (env `EDL_FAULTS`, seed `EDL_FAULTS_SEED`):

    site:action[@key=val[,key=val...]][;site:action@...]

    EDL_FAULTS="rpc.get_task:drop@p=0.05;ckpt.save:crash@at=3"

Sites threaded through the stack (exact-match, or a `prefix.*` wildcard):

    rpc.<method>        before each MasterStub RPC send (proto/service.py);
                        <method> is the snake_case RPC name, e.g.
                        rpc.get_task, rpc.report_task_result, rpc.heartbeat
    rpc.<method>.recv   after the server processed the call, before the
                        response reaches the caller (lost-response shape —
                        the hard case for non-idempotent RPCs)
    worker.heartbeat    each worker heartbeat-loop iteration (worker.py)
    worker.train_step.<id>
                        inside each train step's timed region (worker.py),
                        suffixed with the worker id so a schedule can slow
                        EXACTLY one worker — `worker.train_step.1:delay@
                        ms=40` makes worker 1 a deterministic straggler
                        (the cluster-health scorer's test harness); the
                        `worker.train_step.*` wildcard hits every worker
    worker.report_task  before each task-result report (worker.py)
    ckpt.save           before each checkpoint save (training/checkpoint.py)
    ckpt.save.commit    after the (async) save is initiated, before the
                        caller regains control — `crash` here dies with the
                        write in flight, probing orbax's rename-commit
                        atomicity
    ckpt.restore        before each checkpoint restore attempt
    proc.spawn          before each worker-process spawn
                        (master/process_manager.py); `drop` spawns a process
                        that exits 1 immediately instead of suppressing the
                        spawn (exercising the relaunch path)
    master_crash        each Master.wait poll iteration (master/main.py) —
                        the kill-the-master chaos site. `crash` os._exit's
                        the master process (the true SIGKILL shape when the
                        master runs in its own process); `drop` raises
                        FaultInjected out of wait() — the catchable
                        in-process flavor that client/local.py's
                        --master_restarts recovery path consumes: the master
                        is crashed abruptly and rebuilt on the same port,
                        replaying the control-plane journal
                        (master/journal.py) under a bumped generation
    emb.pull / emb.push / emb.fetch_shard / emb.fetch_delta /
    emb.watermark       REQUEST-side embedding data-plane sites, fired
                        before the owner serves (embedding/transport.py
                        LocalTransport and embedding/data_plane.py
                        GrpcTransport fire identical sites, so one chaos
                        schedule drives either transport)
    emb.pull.recv / emb.push.recv / emb.fetch_shard.recv /
    emb.fetch_delta.recv
                        RESPONSE-side twins, fired after the owner
                        applied/served but before the caller sees the
                        reply — `drop` here is the lost-ack shape: the
                        push LANDED, the caller re-sends under the same
                        seq, and the store's exactly-once fence must
                        absorb the duplicate (pinned over both
                        transports)
    metrics_scrape      each /metrics//healthz HTTP request
                        (observability/http.py). Scraping is strictly
                        best-effort, so the terminal actions are remapped
                        at the site: `drop` aborts the connection with no
                        response; `crash` kills the ENDPOINT (the HTTP
                        server shuts down — NOT the process; training must
                        never die, or even stall, because a scraper did)

Actions:

    drop            raise FaultInjected at the injection point
    delay           sleep `ms` milliseconds (default 100), then continue
    crash           flush the fault trace and os._exit(`code`) (default 1) —
                    the hard-kill shape; nothing downstream runs

Triggers (combinable; a rule fires only when every given trigger agrees):

    p=<float>       fire each hit with this probability, drawn from a
                    per-rule RNG seeded by (seed, site, action) — the same
                    seed + spec reproduces the same decision sequence
    at=<n>          fire exactly on the n-th hit of the site (1-based);
                    `step=` is an accepted alias
    every=<k>       fire every k-th hit
    max=<m>         stop firing after m injections from this rule

With `EDL_FAULTS` unset (the default) every `fire()` call is a two-load
no-op; nothing in this module touches the hot path.

`EDL_FAULTS_TRACE=<path>` appends one line per injected fault
("site:action#hit") at process exit (and before a `crash` exits), so
cross-run determinism is assertable from outside the process.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from dataclasses import dataclass, field
from random import Random
from typing import Dict, List, Optional

from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)

FAULTS_ENV = "EDL_FAULTS"
SEED_ENV = "EDL_FAULTS_SEED"
TRACE_ENV = "EDL_FAULTS_TRACE"

ACTIONS = ("drop", "delay", "crash")

#: pre-crash hooks: called (with the firing site) immediately before a
#: `crash` action's os._exit — which skips atexit, so this is the ONLY
#: chance for a black box (the flight recorder) to hit disk. Process-
#: lifetime: reset()/uninstall() leave them installed. A raising hook is
#: swallowed; the crash must proceed (that is the injected contract).
_CRASH_HOOKS: List = []


def add_crash_hook(fn) -> None:
    """Register `fn(site)` to run before a `crash` action kills the
    process (observability/flight.py wires its bundle dump here)."""
    if fn not in _CRASH_HOOKS:
        _CRASH_HOOKS.append(fn)


def remove_crash_hook(fn) -> None:
    if fn in _CRASH_HOOKS:
        _CRASH_HOOKS.remove(fn)


def _run_crash_hooks(site: str) -> None:
    for hook in list(_CRASH_HOOKS):
        try:
            hook(site)
        except Exception:
            # the simulated kill must happen regardless:
            # edl-lint: disable=EDL303
            logger.exception("pre-crash hook %r failed (ignored)", hook)

# trigger aliases accepted in specs (issue/operator shorthand)
_PARAM_ALIASES = {"step": "at"}
_KNOWN_PARAMS = {"p", "at", "every", "max", "ms", "code"}


class FaultInjected(Exception):
    """Raised at an injection point whose rule decided `drop`."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at {site} (hit {hit})")
        self.site = site
        self.hit = hit


@dataclass
class FaultRule:
    """One `site:action@params` entry of a schedule.

    RNG streams and fire counters are kept PER CONCRETE MATCHED SITE (not
    per rule): a wildcard rule like `rpc.*:drop@p=0.5` would otherwise
    interleave one shared RNG across whichever sites happen to hit first —
    thread scheduling would change the decision sequence and break the
    same-seed reproducibility contract. `max=` likewise caps fires per
    matched site.
    """

    site: str
    action: str
    params: Dict[str, float]
    seed: int = 0
    _rngs: Dict[str, Random] = field(
        repr=False, compare=False, default_factory=dict)
    _fires: Dict[str, int] = field(compare=False, default_factory=dict)

    def matches(self, site: str) -> bool:
        if self.site.endswith(".*"):
            return site.startswith(self.site[:-1]) or site == self.site[:-2]
        return site == self.site

    def decide(self, site: str, hit: int) -> bool:
        """Pure function of (per-site rule state, hit number): fire?

        The per-site RNG is only consumed when a `p` trigger exists and
        the deterministic triggers already agree, so the decision stream
        for a given (seed, site, action) never depends on other rules or
        other sites.
        """
        fires = self._fires.get(site, 0)
        if "max" in self.params and fires >= int(self.params["max"]):
            return False
        if "at" in self.params and hit != int(self.params["at"]):
            return False
        if "every" in self.params and hit % int(self.params["every"]) != 0:
            return False
        if "p" in self.params:
            rng = self._rngs.get(site)
            if rng is None:
                # a string seed makes Random deterministic across processes
                rng = Random(f"{self.seed}:{site}:{self.action}")
                self._rngs[site] = rng
            if rng.random() >= self.params["p"]:
                return False
        self._fires[site] = fires + 1
        return True


@dataclass(frozen=True)
class Fired:
    """A rule firing at a concrete site, with the hit number captured
    under the injector lock (reading the counter later would race)."""

    rule: FaultRule
    site: str
    hit: int

    @property
    def action(self) -> str:
        return self.rule.action

    @property
    def params(self) -> Dict[str, float]:
        return self.rule.params


def parse_spec(spec: str, seed: int = 0) -> List[FaultRule]:
    """Parse an `EDL_FAULTS` schedule into rules (raises ValueError loudly —
    a silently-ignored typo'd schedule would report a vacuous green soak)."""
    rules: List[FaultRule] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        head, _, paramstr = entry.partition("@")
        site, sep, action = head.rpartition(":")
        if not sep or not site:
            raise ValueError(
                f"malformed fault entry {entry!r}: want site:action[@k=v,...]"
            )
        site, action = site.strip(), action.strip()
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r} in {entry!r}; "
                f"choose from {ACTIONS}"
            )
        params: Dict[str, float] = {}
        for kv in filter(None, (s.strip() for s in paramstr.split(","))):
            if "=" not in kv:
                raise ValueError(f"malformed fault param {kv!r} in {entry!r}")
            k, v = kv.split("=", 1)
            k = _PARAM_ALIASES.get(k.strip(), k.strip())
            if k not in _KNOWN_PARAMS:
                raise ValueError(
                    f"unknown fault param {k!r} in {entry!r}; "
                    f"known: {sorted(_KNOWN_PARAMS)}"
                )
            val = float(v)
            # range-check at parse time — a typo'd trigger must fail HERE,
            # loudly, not crash at the injection site (every=0 ->
            # ZeroDivisionError masquerading as a network failure) or
            # silently never fire (p=0, at=0: a vacuous green soak)
            if k == "p" and not 0.0 < val <= 1.0:
                raise ValueError(f"p must be in (0, 1], got {v!r} in {entry!r}")
            if k in ("at", "every", "max") and val < 1:
                raise ValueError(f"{k} must be >= 1, got {v!r} in {entry!r}")
            if k in ("at", "every", "max") and val != int(val):
                # decide() would int()-truncate silently — the same
                # reinterpreted-typo class the checks above reject
                raise ValueError(
                    f"{k} must be an integer, got {v!r} in {entry!r}"
                )
            if k == "ms" and val < 0:
                raise ValueError(f"ms must be >= 0, got {v!r} in {entry!r}")
            params[k] = val
        rules.append(
            FaultRule(site=site, action=action, params=params, seed=seed)
        )
    return rules


class FaultInjector:
    """Holds a parsed schedule and per-site hit counters; thread-safe."""

    def __init__(
        self,
        rules: List[FaultRule],
        seed: int = 0,
        trace_path: Optional[str] = None,
    ):
        self.rules = rules
        self.seed = seed
        # appended under the lock (check); read lock-free by flush_trace
        # (atexit / pre-crash: single-threaded by then) and by tests after
        # the run — a deliberate publication point, not a race
        self.trace: List[str] = []
        self._trace_path = trace_path
        self._hits: Dict[str, int] = {}      # guarded_by: _lock
        self._lock = threading.Lock()
        self._trace_flushed = False
        if trace_path:
            atexit.register(self.flush_trace)

    @classmethod
    def from_spec(
        cls, spec: str, seed: int = 0, trace_path: Optional[str] = None
    ) -> "FaultInjector":
        return cls(parse_spec(spec, seed), seed=seed, trace_path=trace_path)

    # ------------------------------------------------------------------ #

    def check(self, site: str) -> Optional[Fired]:
        """Count a hit at `site` and return the firing, if any.

        Extension point for call sites needing custom handling of terminal
        actions; everything in-tree goes through fire().
        """
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            for rule in self.rules:
                if rule.matches(site) and rule.decide(site, hit):
                    self.trace.append(f"{site}:{rule.action}#{hit}")
                    logger.warning(
                        "FAULT INJECTED: %s -> %s (hit %d)",
                        site, rule.action, hit,
                    )
                    return Fired(rule=rule, site=site, hit=hit)
        return None

    def fire(self, site: str) -> None:
        """Inject at `site`: no-op, sleep, raise, or kill the process."""
        fired = self.check(site)
        if fired is None:
            return
        if fired.action == "delay":
            # the injected stall IS the fault being simulated — callers
            # holding locks across fire() is exactly the stall-under-lock
            # behavior chaos legs exist to exercise:
            # edl-lint: disable=EDL103
            time.sleep(fired.params.get("ms", 100.0) / 1000.0)
        elif fired.action == "drop":
            raise FaultInjected(site, fired.hit)
        elif fired.action == "crash":
            # black-box dumps first (os._exit skips atexit and excepthook:
            # the flight recorder would otherwise die with its evidence)
            _run_crash_hooks(site)
            self.flush_trace()
            os._exit(int(fired.params.get("code", 1)))

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def flush_trace(self) -> None:
        """Append the trace to `trace_path` once (idempotent; also runs via
        atexit, and explicitly before a `crash` action's os._exit, which
        would skip atexit handlers)."""
        if not self._trace_path or self._trace_flushed:
            return
        self._trace_flushed = True
        try:
            # last-gasp evidence dump on the atexit / pre-os._exit crash
            # path — the process is dying, nothing queues behind it:
            # edl-lint: disable=EDL103
            with open(self._trace_path, "a") as f:
                for line in self.trace:
                    f.write(line + "\n")
        except OSError:
            logger.exception("fault trace flush to %s failed", self._trace_path)


# ---------------------------------------------------------------------- #
# module-level singleton (lazily initialized from the environment)

_injector: Optional[FaultInjector] = None
_initialized = False
_init_lock = threading.Lock()


def get_injector() -> Optional[FaultInjector]:
    global _injector, _initialized
    if not _initialized:
        with _init_lock:
            if not _initialized:
                spec = os.environ.get(FAULTS_ENV, "")
                if spec:
                    _injector = FaultInjector.from_spec(
                        spec,
                        seed=int(os.environ.get(SEED_ENV, "0") or 0),
                        trace_path=os.environ.get(TRACE_ENV) or None,
                    )
                    logger.warning(
                        "fault injection ACTIVE: %d rule(s) from %s (seed %s)",
                        len(_injector.rules), FAULTS_ENV, _injector.seed,
                    )
                _initialized = True
    return _injector


def install(
    spec: str, seed: int = 0, trace_path: Optional[str] = None
) -> FaultInjector:
    """Install a schedule programmatically (tests); replaces any active one."""
    global _injector, _initialized
    with _init_lock:
        _injector = FaultInjector.from_spec(spec, seed, trace_path)
        _initialized = True
    return _injector


def uninstall() -> None:
    """Disable injection for this process (does not re-read the env)."""
    global _injector, _initialized
    with _init_lock:
        _injector = None
        _initialized = True


def reset() -> None:
    """Forget everything; the next fire() re-reads the environment."""
    global _injector, _initialized
    with _init_lock:
        _injector = None
        _initialized = False


def fire(site: str) -> None:
    """The injection point. A cheap no-op when no schedule is active."""
    inj = _injector if _initialized else get_injector()
    if inj is not None:
        inj.fire(site)


def check(site: str) -> Optional[Fired]:
    """Like fire(), but returns the firing for call-site-custom handling
    instead of acting (still counts the hit and records the trace).
    `delay` rules are slept here so custom sites only need to branch on
    terminal actions."""
    inj = _injector if _initialized else get_injector()
    if inj is None:
        return None
    fired = inj.check(site)
    if fired is not None and fired.action == "delay":
        time.sleep(fired.params.get("ms", 100.0) / 1000.0)
    return fired
