"""Small networking helpers shared by launchers and managers."""

from __future__ import annotations

import socket
from contextlib import closing


def free_port() -> int:
    with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.bind(("", 0))
        return s.getsockname()[1]
