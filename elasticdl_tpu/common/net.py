"""Small networking helpers shared by launchers and managers."""

from __future__ import annotations

import socket
from contextlib import closing
from typing import Callable, Tuple, TypeVar

T = TypeVar("T")


class PortBindError(RuntimeError):
    """A server lost the free_port() TOCTOU race: the port looked free when
    picked but was taken (EADDRINUSE) by the time the server bound it."""


def free_port() -> int:
    """Pick an ephemeral port that was free a moment ago. Inherently TOCTOU
    — another process can grab it before the caller binds. Callers that go
    on to bind a server should do so through bind_with_retry(); the cohort
    coordinator (whose binder is a child process) instead retries whole
    world formations budget-free on ExitCode.WORLD_FORM_FAILED."""
    with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def bind_with_retry(
    build: Callable[[int], T], attempts: int = 5
) -> Tuple[int, T]:
    """Close the free_port() TOCTOU window: pick a fresh ephemeral port and
    call `build(port)` (which must bind it, raising PortBindError when the
    bind is lost to the race), retrying with a new port up to `attempts`
    times. Returns (port, build's result)."""
    last: PortBindError
    for _ in range(max(1, attempts)):
        port = free_port()
        try:
            return port, build(port)
        except PortBindError as e:
            last = e
    raise last
