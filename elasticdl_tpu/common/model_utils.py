"""Model-zoo module loading.

Reference parity: elasticdl/python/common/model_utils.py — resolve the user's
model by dotted path (`--model_def=mnist.mnist_cnn.custom_model`), add the
model-zoo root to sys.path, and find the companion functions
(`loss`, `optimizer`, `dataset_fn`, `eval_metrics_fn`, `callbacks`) in the
same module, each overridable by its own flag.
"""

from __future__ import annotations

import importlib
import os
import sys
from types import ModuleType
from typing import Any, Callable, Optional


def load_module(model_zoo: str, dotted: str) -> tuple[ModuleType, str]:
    """Load `pkg.module` of `pkg.module.func` under the model_zoo root.

    Returns (module, func_name).
    """
    if not dotted:
        raise ValueError("empty model_def")
    root = os.path.abspath(model_zoo)
    if root not in sys.path:
        sys.path.insert(0, root)
    module_path, _, func_name = dotted.rpartition(".")
    if not module_path:
        raise ValueError(
            f"model_def must be 'package.module.function', got {dotted!r}"
        )
    module = importlib.import_module(module_path)
    return module, func_name


def get_module_attr(
    module: ModuleType, name: str, override: str = "", required: bool = True
) -> Optional[Callable[..., Any]]:
    """Fetch a contract function from the model module.

    `override` is a flag like the reference's `--loss=my_loss`: either a bare
    name looked up in the same module, or a fully dotted path to elsewhere.
    """
    if override:
        if "." in override:
            mod_path, _, fn = override.rpartition(".")
            other = importlib.import_module(mod_path)
            attr = getattr(other, fn, None)
        else:
            attr = getattr(module, override, None)
        if attr is None:
            # an explicit override that resolves to nothing is always an
            # error, even for optional contract functions — fail fast
            raise ValueError(f"override {override!r} not found for {name}")
        return attr
    attr = getattr(module, name, None)
    if attr is None and required:
        raise ValueError(
            f"model module {module.__name__!r} defines no {name}() and no override given"
        )
    return attr
