"""Master -> worker pending-membership announcement (file-based).

The rescale fast path wants workers to know a resize is COMING before the
teardown lands, so the speculative compiler can precompile the announced
world size (training/compile_cache.py). The natural channel would be a
`pending_world_size` field on HeartbeatResponse, but this image's proto
toolchain cannot regenerate message bindings (no protoc/grpcio-tools), so
the announcement rides a small JSON file on storage both sides already
share — the log/checkpoint directory for the local process manager, a
mounted volume or ConfigMap in the k8s flavor. Writes are atomic
(tmp + rename), readers tolerate a missing/garbled file (None), and the
file is advisory: losing it degrades to the pre-announcement behavior
(the resize still happens, just against a colder cache).

The process manager exports the path to spawned workers as
`EDL_PENDING_WORLD_FILE`.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

ENV_VAR = "EDL_PENDING_WORLD_FILE"

logger = logging.getLogger(__name__)


def write_signal(
    path: str,
    *,
    world_size: int,
    pending_size: Optional[int] = None,
    world_version: int = 0,
    trace_id: Optional[str] = None,
    master_generation: int = 0,
) -> bool:
    """Atomically (re)write the membership signal. Best-effort: a failed
    write is logged and must never take the caller (the master's watch
    loop) down with it.

    `trace_id` stitches the resize's observability timeline across roles:
    the master stamps the reform trace id here, workers adopt it for their
    rescale/boot spans (observability/tracing.py) — one resize, one trace
    id in both `trace.jsonl` files.

    `master_generation` (master/journal.py; 0 = no journal) marks WHICH
    master wrote the announcement, so a reader — and a successor master at
    takeover — can tell a live plan from one a dead master left behind."""
    payload = {
        "world_size": int(world_size),
        "pending_size": None if pending_size is None else int(pending_size),
        "world_version": int(world_version),
        "trace_id": trace_id or None,
        "master_generation": int(master_generation),
    }
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return True
    except OSError:
        logger.exception("membership signal write failed (%s)", path)
        return False


def read_signal(path: Optional[str] = None) -> Optional[dict]:
    """Read the signal file (default: $EDL_PENDING_WORLD_FILE). None when
    unset, missing, or unreadable — all meaning 'no announcement'."""
    path = path or os.environ.get(ENV_VAR, "")
    if not path:
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def trace_id(path: Optional[str] = None) -> Optional[str]:
    """The announced resize's trace id, or None (no announcement / an
    announcement written before tracing existed)."""
    data = read_signal(path)
    if not data:
        return None
    tid = data.get("trace_id")
    return str(tid) if tid else None


def pending_size(path: Optional[str] = None) -> Optional[int]:
    """The announced next world size, or None when nothing is pending."""
    data = read_signal(path)
    if not data:
        return None
    pending = data.get("pending_size")
    try:
        return int(pending) if pending is not None else None
    except (TypeError, ValueError):
        return None


def master_generation(path: Optional[str] = None) -> int:
    """The generation of the master that wrote the signal (0 = unknown /
    written by a journal-less master)."""
    data = read_signal(path)
    if not data:
        return 0
    try:
        return int(data.get("master_generation") or 0)
    except (TypeError, ValueError):
        return 0


def default_path(base_dir: str = "") -> str:
    """Where the signal file lives for this process: the exported env path
    when the process manager set one, else `<base_dir>/membership_signal.json`
    (the manager's own default base is its log dir or the checkpoint dir).
    "" when neither is known."""
    env_path = os.environ.get(ENV_VAR, "")
    if env_path:
        return env_path
    return os.path.join(base_dir, "membership_signal.json") if base_dir else ""


def clear_stale_on_takeover(path: str, *, master_generation: int) -> bool:
    """A restarted master takes over: drop the dead master's announced plan
    (pending world size + reform trace id) so workers' speculative
    compilers stop precompiling against it, and stamp the file with the new
    master generation. The observed world_size/world_version survive — they
    describe the workers, which did not restart. No file, nothing stale:
    returns False without creating one (the next real announcement will).
    """
    data = read_signal(path)
    if data is None:
        return False
    ok = write_signal(
        path,
        world_size=int(data.get("world_size") or 0),
        pending_size=None,
        world_version=int(data.get("world_version") or 0),
        trace_id=None,
        master_generation=master_generation,
    )
    if ok:
        logger.warning(
            "membership signal cleared at master takeover (generation %d): "
            "pending plan %r dropped", master_generation,
            data.get("pending_size"),
        )
    return ok
