"""Master -> worker pending-membership announcement (file-based).

The rescale fast path wants workers to know a resize is COMING before the
teardown lands, so the speculative compiler can precompile the announced
world size (training/compile_cache.py). The natural channel would be a
`pending_world_size` field on HeartbeatResponse, but this image's proto
toolchain cannot regenerate message bindings (no protoc/grpcio-tools), so
the announcement rides a small JSON file on storage both sides already
share — the log/checkpoint directory for the local process manager, a
mounted volume or ConfigMap in the k8s flavor. Writes are atomic
(tmp + rename), readers tolerate a missing/garbled file (None), and the
file is advisory: losing it degrades to the pre-announcement behavior
(the resize still happens, just against a colder cache).

The process manager exports the path to spawned workers as
`EDL_PENDING_WORLD_FILE`.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

ENV_VAR = "EDL_PENDING_WORLD_FILE"

logger = logging.getLogger(__name__)


def write_signal(
    path: str,
    *,
    world_size: int,
    pending_size: Optional[int] = None,
    world_version: int = 0,
    trace_id: Optional[str] = None,
) -> bool:
    """Atomically (re)write the membership signal. Best-effort: a failed
    write is logged and must never take the caller (the master's watch
    loop) down with it.

    `trace_id` stitches the resize's observability timeline across roles:
    the master stamps the reform trace id here, workers adopt it for their
    rescale/boot spans (observability/tracing.py) — one resize, one trace
    id in both `trace.jsonl` files."""
    payload = {
        "world_size": int(world_size),
        "pending_size": None if pending_size is None else int(pending_size),
        "world_version": int(world_version),
        "trace_id": trace_id or None,
    }
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return True
    except OSError:
        logger.exception("membership signal write failed (%s)", path)
        return False


def read_signal(path: Optional[str] = None) -> Optional[dict]:
    """Read the signal file (default: $EDL_PENDING_WORLD_FILE). None when
    unset, missing, or unreadable — all meaning 'no announcement'."""
    path = path or os.environ.get(ENV_VAR, "")
    if not path:
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def trace_id(path: Optional[str] = None) -> Optional[str]:
    """The announced resize's trace id, or None (no announcement / an
    announcement written before tracing existed)."""
    data = read_signal(path)
    if not data:
        return None
    tid = data.get("trace_id")
    return str(tid) if tid else None


def pending_size(path: Optional[str] = None) -> Optional[int]:
    """The announced next world size, or None when nothing is pending."""
    data = read_signal(path)
    if not data:
        return None
    pending = data.get("pending_size")
    try:
        return int(pending) if pending is not None else None
    except (TypeError, ValueError):
        return None
