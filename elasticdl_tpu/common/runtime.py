"""Per-process JAX runtime setup shared by both worker flavors.

Reference analog: none — upstream's TF2 runtime had no compile step to
cache. Here it matters doubly: (1) first XLA compilation of a real model on
TPU is 20-40 s, and (2) elastic recovery RELAUNCHES worker processes
(process_manager/k8s_instance_manager), so without a persistent cache every
preemption pays the full recompile on top of restore — measured: cohort
kill -> first-task-at-new-size was ~10.6 s on the CPU test mesh, most of it
world re-boot + compile (BASELINE.md round-3 log). With
`--compilation_cache_dir` the relaunched generation deserializes the
previous generation's executables instead.
"""

from __future__ import annotations

import os

from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


def configure_jax_runtime(cfg) -> None:
    """Apply config-driven JAX process settings. Call before building
    trainers/meshes (idempotent; safe to call from every entrypoint).

    `EDL_COMPILATION_CACHE_DIR` overrides an empty config value: re-formed
    worker generations inherit the cache location through the environment
    even when the job's immutable argv never carried it (the rescale fast
    path's cross-process warmth channel)."""
    cache_dir = (
        getattr(cfg, "compilation_cache_dir", "")
        or os.environ.get("EDL_COMPILATION_CACHE_DIR", "")
    )
    if cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        min_compile_s = getattr(cfg, "compilation_cache_min_compile_s", -1.0)
        if min_compile_s >= 0:
            # explicit floor override (tests set 0 so even test-sized
            # programs cache); production keeps JAX's defaults — writing
            # every sub-second jit to shared storage is churn, not savings
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(min_compile_s),
            )
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        logger.info("persistent XLA compilation cache at %s", cache_dir)
