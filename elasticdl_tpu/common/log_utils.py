"""Uniform logger factory.

Reference parity: elasticdl/python/common/log_utils.py — plus the
observability hooks: `EDL_LOG_JSON=1` switches the formatter to structured
JSON lines carrying `role`, `world_version`, and the active
`trace_id`/`span_id` (so log lines join against trace.jsonl on trace id),
and the plain format gains a `[role]` prefix once a role is set.

The trace context comes from a registered provider
(observability/tracing.py injects `context_for_logs` at import) — this
module stays import-cycle-free and usable before observability loads.
"""

import json
import logging
import os
import sys
import time
from typing import Callable, Dict, Optional

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(name)s:%(lineno)d] %(message)s"
)

_configured = False
_role = ""
# () -> {"role": ..., "world_version": ..., "trace_id"?, "span_id"?}
_context_provider: Optional[Callable[[], Dict[str, object]]] = None


def set_role(role: str) -> None:
    """Stamp this process's role (master / worker-N / bench) on every log
    record — and, through observability.tracing, on every span."""
    global _role
    _role = role


def get_role() -> str:
    return _role


def set_context_provider(fn: Callable[[], Dict[str, object]]) -> None:
    """Register the trace-context source for formatters (called by
    observability.tracing at import; injectable for tests)."""
    global _context_provider
    _context_provider = fn


def _context() -> Dict[str, object]:
    ctx: Dict[str, object] = {}
    if _context_provider is not None:
        try:
            ctx = dict(_context_provider())
        except Exception:
            ctx = {}
    if _role and not ctx.get("role"):
        ctx["role"] = _role
    return ctx


class _PlainFormatter(logging.Formatter):
    """The classic format, prefixed with the role once one is known."""

    def format(self, record: logging.LogRecord) -> str:
        line = super().format(record)
        role = _context().get("role")
        return f"[{role}] {line}" if role else line


class _JsonFormatter(logging.Formatter):
    """One JSON object per line, joinable against trace.jsonl: shares the
    `role` / `world_version` / `trace_id` / `span_id` keys and schema."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, object] = {
            "ts": round(record.created or time.time(), 6),
            "level": record.levelname,
            "logger": record.name,
            "line": record.lineno,
            "msg": record.getMessage(),
        }
        out.update(_context())
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def make_formatter() -> logging.Formatter:
    if os.environ.get("EDL_LOG_JSON", "") in ("1", "true", "yes"):
        return _JsonFormatter()
    return _PlainFormatter(_FORMAT)


def default_logger(name: str = "elasticdl_tpu") -> logging.Logger:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(make_formatter())
        root = logging.getLogger("elasticdl_tpu")
        root.addHandler(handler)
        root.setLevel(os.environ.get("EDL_LOG_LEVEL", "INFO").upper())
        root.propagate = False
        _configured = True
    return logging.getLogger(name)
