"""Uniform logger factory.

Reference parity: elasticdl/python/common/log_utils.py.
"""

import logging
import os
import sys

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(name)s:%(lineno)d] %(message)s"
)

_configured = False


def default_logger(name: str = "elasticdl_tpu") -> logging.Logger:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root = logging.getLogger("elasticdl_tpu")
        root.addHandler(handler)
        root.setLevel(os.environ.get("EDL_LOG_LEVEL", "INFO").upper())
        root.propagate = False
        _configured = True
    return logging.getLogger(name)
