"""Framework-wide constants.

Reference parity: elasticdl/python/common/constants.py (GRPC message sizes,
pod/label names, checkpoint dir layout).
"""


class GRPC:
    # Embedding pulls and dense model pushes can be large; match the
    # reference's practice of raising the default 4 MB gRPC cap.
    MAX_SEND_MESSAGE_LENGTH = 256 * 1024 * 1024
    MAX_RECEIVE_MESSAGE_LENGTH = 256 * 1024 * 1024
    OPTIONS = [
        ("grpc.max_send_message_length", MAX_SEND_MESSAGE_LENGTH),
        ("grpc.max_receive_message_length", MAX_RECEIVE_MESSAGE_LENGTH),
    ]


class TaskType:
    """Task types leased by the master to workers.

    Reference parity: elasticdl.proto's TaskType enum
    (TRAINING / EVALUATION / PREDICTION / SAVE_MODEL / WAIT).
    """

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    SAVE_MODEL = "save_model"
    WAIT = "wait"


class JobType:
    TRAINING_ONLY = "training_only"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"
    EVALUATION_ONLY = "evaluation_only"
    PREDICTION_ONLY = "prediction_only"


class PodStatus:
    """Lifecycle states of a managed worker instance.

    Mirrors the k8s pod phases the reference's instance manager watches
    (reference: elasticdl/python/master/k8s_instance_manager.py).
    """

    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"


class WorkerEnv:
    """Environment variables the launcher sets on each worker process."""

    WORKER_ID = "EDL_WORKER_ID"
    MASTER_ADDR = "EDL_MASTER_ADDR"
    NUM_WORKERS = "EDL_NUM_WORKERS"
    COORDINATOR_ADDR = "EDL_COORDINATOR_ADDR"


class ExitCode:
    """Worker exit codes the process manager keys recovery decisions on."""

    OK = 0
    # EX_TEMPFAIL: evicted/preempted mid-job — relaunch me
    COHORT_EVICTED = 75
    # jax.distributed world never formed (e.g. coordinator-port TOCTOU);
    # an infrastructure failure that must not consume the relaunch budget
    WORLD_FORM_FAILED = 76


class MeshAxis:
    """Canonical mesh axis names for every sharding in the framework."""

    DATA = "data"   # batch dimension; DP gradient psum rides this axis
    MODEL = "model"  # embedding-table rows / any model-parallel dim
    SEQ = "seq"     # sequence/context parallelism (ring / Ulysses attention)
    PIPE = "pp"     # pipeline parallelism (GPipe microbatch streaming)
    EXPERT = "expert"  # expert parallelism (MoE all_to_all dispatch)


DEFAULT_MASTER_PORT = 50001

# TPU accelerator type → (gke accelerator label, topology, hosts, chips/host).
# Lives here (not client/k8s.py) so config validation can reason about slice
# shape without importing the client layer.
TPU_TYPES = {
    "v5e-4": ("tpu-v5-lite-podslice", "2x2", 1, 4),
    "v5e-8": ("tpu-v5-lite-podslice", "2x4", 2, 4),
    "v5e-16": ("tpu-v5-lite-podslice", "4x4", 4, 4),
    "v5e-32": ("tpu-v5-lite-podslice", "4x8", 8, 4),
    "v5e-64": ("tpu-v5-lite-podslice", "8x8", 16, 4),
    "v5p-8": ("tpu-v5p-slice", "2x2x1", 2, 4),
    "v4-8": ("tpu-v4-podslice", "2x2x1", 2, 4),
}
