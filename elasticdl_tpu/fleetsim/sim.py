"""The fleet simulator core: thousands of scripted workers vs the REAL
master control plane, on a compressed virtual clock.

Everything master-side is production code, not a mock: the journal
(with its real group-commit window and real fsyncs), Membership,
TaskDispatcher, ClusterHealth, FleetGoodput, the TimeSeriesStore, the
AlertEngine, and the Autoscaler behind a simulator-backed scale target.
Only the WIRE is simulated — workers call `MasterServicer` methods
directly through a `SimContext` that carries the same invocation
metadata (generation claim, re-register flag, stats payload) a gRPC
hop would, and `abort()` raises `SimRpcError` the way grpc raises
RpcError, so the generation fence / re-register handshake is exercised
verbatim.

Time model (the load-bearing trick):

- **Virtual time** orders the fleet: a single-threaded discrete-event
  scheduler pops (virtual_offset, seq, callback) off a heap and jumps
  the clock between events, so a 10-minute soak with 1000 workers runs
  in seconds of wall. Every master component gets the virtual clock
  injected (``clock=vclock.now``), so lease timeouts, heartbeat reaping,
  alert windows and autoscale cooldowns all happen at fleet-realistic
  VIRTUAL rates.
- **Real time** measures the master: journal flush latency, poll-phase
  wall (master/poll_phases.py), lock passes — the costs the soak exists
  to find — are measured with perf_counter, untouched by compression.

Determinism: one seed drives every RNG (the fleet RNG and one
`random.Random` per worker), scheduling ties break on insertion order,
and the event log records only virtual offsets — the same scenario +
seed yields an identical event log and identical journal accounting on
every run (pinned by tests/test_fleetsim.py). NEVER call `time.sleep`
in this package: sleeping real time inside simulated time is always a
bug (edl-lint EDL502).
"""

from __future__ import annotations

import hashlib
import heapq
import json
import math
import os
import random
import shutil
import time
from contextlib import redirect_stdout
from typing import Any, Callable, Dict, List, Optional

import grpc

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.fleetsim.scenario import Scenario
from elasticdl_tpu.master.poll_phases import poll_phase
from elasticdl_tpu.observability import health as health_lib
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.proto.service import GENERATION_KEY, REREGISTER_KEY

#: virtual seconds a grow action takes to materialize a bootable worker
#: (instance provision + container pull, compressed)
PROVISION_DELAY_S = 5.0

#: goodput ledger categories a sim worker reports (cumulative seconds;
#: mirrors observability/goodput.py GoodputLedger.CATEGORIES)
GP_KEYS = (
    "gp_wall_s", "gp_train_compute_s", "gp_data_wait_s", "gp_h2d_s",
    "gp_emb_pull_blocked_s", "gp_rescale_s", "gp_lease_wait_s",
    "gp_reconnect_s", "gp_overhead_s",
)


class SimRpcError(Exception):
    """The sim's stand-in for grpc.RpcError: raised by SimContext.abort
    and by calls against a down master."""

    def __init__(self, code, details: str = ""):
        super().__init__(f"{code}: {details}")
        self.status_code = code
        self.details = details

    @property
    def unavailable(self) -> bool:
        return self.status_code == grpc.StatusCode.UNAVAILABLE

    @property
    def stale_generation(self) -> bool:
        return self.status_code == grpc.StatusCode.FAILED_PRECONDITION


class SimContext:
    """A servicer-side context faithful to the slice of grpc.ServicerContext
    the master actually uses: invocation metadata in, abort out."""

    __slots__ = ("_metadata",)

    def __init__(self, metadata=()):
        self._metadata = tuple(metadata)

    def invocation_metadata(self):
        return self._metadata

    def abort(self, code, details: str = "") -> None:
        raise SimRpcError(code, details)

    def set_trailing_metadata(self, md) -> None:  # parity no-op
        pass


class VirtualClock:
    """Wall-anchored virtual time: now() = real epoch at run start +
    virtual offset. Anchoring at a real epoch keeps journaled timestamps
    plausible (the incident CLI renders them); all DECISIONS downstream
    depend only on deltas, which are pure virtual and deterministic."""

    def __init__(self):
        self.base = time.time()
        self.offset = 0.0

    def now(self) -> float:
        return self.base + self.offset


class Scheduler:
    """Deterministic discrete-event loop: (offset, seq, fn) min-heap;
    ties break on insertion order."""

    def __init__(self, clock: VirtualClock):
        self._clock = clock
        self._heap: List[tuple] = []
        self._seq = 0

    def at(self, offset: float, fn: Callable[[], None]) -> None:
        # the past is not schedulable: clamp to "now" (a callback that
        # computes a tiny negative delay must not rewind the clock)
        heapq.heappush(
            self._heap, (max(offset, self._clock.offset), self._seq, fn))
        self._seq += 1

    def after(self, delay: float, fn: Callable[[], None]) -> None:
        self.at(self._clock.offset + max(0.0, delay), fn)

    def run(self, until: float,
            stop_fn: Optional[Callable[[], bool]] = None) -> None:
        while self._heap:
            offset, _seq, fn = heapq.heappop(self._heap)
            if offset > until:
                break
            self._clock.offset = offset
            fn()
            if stop_fn is not None and stop_fn():
                break


class EventLog:
    """The run's deterministic record: virtual offsets only, never real
    wall — same seed, same bytes (the determinism test hashes this)."""

    def __init__(self):
        self.entries: List[Dict[str, Any]] = []

    def log(self, clock: VirtualClock, kind: str, **fields) -> None:
        entry = {"at_s": round(clock.offset, 3), "event": kind}
        entry.update(fields)
        self.entries.append(entry)

    def digest(self) -> str:
        blob = json.dumps(self.entries, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()


# --------------------------------------------------------------------- #
# the scripted worker


class SimWorker:
    """One scripted worker lifecycle against the real master: register →
    heartbeat (honest stats payload) → lease batches → report → die /
    rejoin, with the production reconnect handshake (UNAVAILABLE backoff,
    generation fence → re-register) on every path."""

    def __init__(self, fleet: "FleetSim", sim_id: int):
        self.fleet = fleet
        self.sim_id = sim_id
        self.rack = sim_id % fleet.scenario.racks
        self.name = f"sim-{sim_id}"
        sc = fleet.scenario
        self.rng = random.Random(((sc.seed + 1) << 20) ^ sim_id)
        self.alive = False
        self.evicted = False          # terminal: never rejoins
        self.registered = False
        self.incarnation = 0          # bumps cancel scheduled callbacks
        self.worker_id = -1
        self.generation = 0           # master generation claimed on calls
        self.steps = 0
        self.straggle_factor = 1.0
        self.straggle_until = 0.0     # virtual offset
        self.data_wait_frac = sc.data_wait_frac
        self.emb: Dict[str, float] = {}   # popularity_flip payload fields
        self.gp = {k: 0.0 for k in GP_KEYS}
        self._ledger_mark = 0.0       # virtual offset of last ledger cut
        self._pend_reconnect = 0.0    # virtual s since last cut
        self._pend_lease_wait = 0.0
        self._backoff = 0.0
        self.held: List[Any] = []     # leased task protos awaiting report

    # -- lifecycle ----------------------------------------------------- #

    def boot(self, delay: float) -> None:
        self.incarnation += 1
        self.alive = True
        self.registered = False
        self.held.clear()
        self._backoff = 0.0
        self._ledger_mark = self.fleet.vclock.offset + delay
        inc = self.incarnation
        self.fleet.sched.after(delay, lambda: self._register(inc))

    def die(self) -> None:
        """Abrupt death: stops beating mid-lease; nothing is reported.
        The master finds out the hard way (heartbeat reap → task
        recovery), exactly like a real SIGKILL'd worker."""
        if not self.alive:
            return
        self.incarnation += 1
        self.alive = False
        self.registered = False
        self.held.clear()

    def rejoin(self, delay: float) -> None:
        if self.alive or self.evicted:
            return
        self.boot(delay)

    def _stale(self, inc: int) -> bool:
        return inc != self.incarnation or not self.alive

    def _next_backoff(self) -> float:
        base = 1.0 if self._backoff <= 0 else min(10.0, self._backoff * 2)
        self._backoff = base
        return base * (0.75 + 0.5 * self.rng.random())

    def _refence(self) -> None:
        """A FAILED_PRECONDITION (stale master generation) on any call:
        drop leases (the replayed master already requeued them), cancel
        every scheduled callback for this life, and re-enter through the
        re-register handshake."""
        self.incarnation += 1
        self.registered = False
        self.held.clear()
        self._backoff = 0.0
        inc = self.incarnation
        self.fleet.stat["fences_seen"] += 1
        self.fleet.sched.after(
            0.05 + 0.2 * self.rng.random(), lambda: self._register(inc))

    # -- register ------------------------------------------------------ #

    def _register(self, inc: int) -> None:
        if self._stale(inc):
            return
        fleet = self.fleet
        sc = fleet.scenario
        reconnect = self.worker_id >= 0
        md = ((REREGISTER_KEY, "1"),) if reconnect else ()
        req = pb.RegisterWorkerRequest(
            worker_name=self.name,
            preferred_id_plus_one=(self.worker_id + 1) if reconnect else 0,
            member_names=[
                f"{self.name}#p{j + 1}" for j in range(sc.cohort_members)
            ],
        )
        try:
            resp = fleet.rpc("RegisterWorker", req, md)
        except SimRpcError as e:
            if e.stale_generation:
                # register itself never claims a generation; structurally
                # unreachable, but a worker must not crash on any abort
                self._refence()
                return
            delay = self._next_backoff()
            self._pend_reconnect += delay
            fleet.sched.after(delay, lambda: self._register(inc))
            return
        self._backoff = 0.0
        first = self.worker_id < 0
        self.worker_id = resp.worker_id
        self.generation = fleet.generation
        self.registered = True
        fleet.stat["registrations" if first else "reregistrations"] += 1
        if first:
            fleet.events.log(fleet.vclock, "worker_up",
                             sim_id=self.sim_id, worker_id=self.worker_id,
                             rack=self.rack)
        jitter = self.rng.random()
        fleet.sched.after(sc.heartbeat_s * (0.5 + 0.5 * jitter),
                          lambda: self._heartbeat(inc))
        fleet.sched.after(0.01 + 0.05 * jitter, lambda: self._lease(inc))

    # -- heartbeat + stats payload ------------------------------------- #

    def _payload(self) -> Dict[str, Any]:
        sc = self.fleet.scenario
        factor = self.straggle_factor
        step_ms = sc.step_ms * factor
        dw = self.data_wait_frac
        payload: Dict[str, Any] = {
            "steps": self.steps,
            "step_p50_ms": round(step_ms, 3),
            "step_p90_ms": round(step_ms * 1.2, 3),
            "step_max_ms": round(step_ms * 1.7, 3),
            "records_per_s": round(sc.records_per_s / factor, 2),
            "phase": "train",
            "phase_data_wait_ms": round(step_ms * dw, 3),
            "phase_compute_ms": round(step_ms * (1.0 - dw), 3),
        }
        for k, v in self.gp.items():
            payload[k] = round(v, 3)
        payload.update(self.emb)
        if self.emb and self.fleet.layout_ctl is not None:
            # layout runs: the flipped workers' embedding telemetry is
            # recomputed against the CURRENT shard map every beat, so
            # the controller's own actions show up in the next sample
            payload.update(self.fleet.layout_emb_stats())
        return payload

    def _cut_ledger(self) -> None:
        """Attribute virtual wall since the last cut across the goodput
        categories: total-attribution invariant (categories sum to
        wall), like the real GoodputLedger."""
        now = self.fleet.vclock.offset
        delta = max(0.0, now - self._ledger_mark)
        self._ledger_mark = now
        reconnect = min(self._pend_reconnect, delta)
        lease_wait = min(self._pend_lease_wait, delta - reconnect)
        self._pend_reconnect = self._pend_lease_wait = 0.0
        rest = delta - reconnect - lease_wait
        overhead = rest * 0.02
        data_wait = (rest - overhead) * self.data_wait_frac
        compute = rest - overhead - data_wait
        self.gp["gp_wall_s"] += delta
        self.gp["gp_reconnect_s"] += reconnect
        self.gp["gp_lease_wait_s"] += lease_wait
        self.gp["gp_overhead_s"] += overhead
        self.gp["gp_data_wait_s"] += data_wait
        self.gp["gp_train_compute_s"] += compute
        step_s = (self.fleet.scenario.step_ms / 1e3) * self.straggle_factor
        if step_s > 0:
            self.steps += int(compute / step_s)

    def _heartbeat(self, inc: int) -> None:
        if self._stale(inc):
            return
        fleet = self.fleet
        sc = fleet.scenario
        if self.straggle_factor != 1.0 \
                and fleet.vclock.offset >= self.straggle_until:
            self.straggle_factor = 1.0
        self._cut_ledger()
        payload = self._payload()
        md = [
            (GENERATION_KEY, str(self.generation)),
            (health_lib.STATS_METADATA_KEY, health_lib.encode_stats(payload)),
        ]
        members = [
            pb.MemberBeat(
                worker_id=mid, model_version=self.steps,
                stats_json=health_lib.encode_stats(payload),
            )
            for mid in fleet.cohort_member_ids(self.worker_id)
        ]
        req = pb.HeartbeatRequest(
            worker_id=self.worker_id, model_version=self.steps,
            members=members,
        )
        try:
            resp = fleet.rpc("Heartbeat", req, md)
        except SimRpcError as e:
            if e.stale_generation:
                self._refence()
                return
            delay = self._next_backoff()
            self._pend_reconnect += delay
            fleet.sched.after(delay, lambda: self._heartbeat(inc))
            return
        self._backoff = 0.0
        fleet.stat["heartbeats"] += 1
        if resp.evict:
            self._drain_evicted(inc)
            return
        if resp.job_done:
            self.die()
            return
        if resp.shutdown:
            # the master no longer knows us (reaped while partitioned,
            # same generation): an elastic worker re-enters through the
            # re-register handshake instead of exiting
            self._refence()
            return
        fleet.sched.after(sc.heartbeat_s * (0.9 + 0.2 * self.rng.random()),
                          lambda: self._heartbeat(inc))

    def _drain_evicted(self, inc: int) -> None:
        """The autoscaler's drain handshake: report outstanding leases
        preempted (requeued without a retry penalty), then leave for
        good."""
        fleet = self.fleet
        for task in list(self.held):
            req = pb.ReportTaskResultRequest(
                worker_id=self.worker_id, task_id=task.task_id,
                success=False, err_message="evicted", preempted=True,
                model_version=self.steps,
            )
            try:
                fleet.rpc("ReportTaskResult", req,
                          [(GENERATION_KEY, str(self.generation))])
            except SimRpcError:
                break   # requeue happens master-side either way
        self.held.clear()
        self.die()
        self.evicted = True
        fleet.stat["evictions_drained"] += 1
        fleet.events.log(fleet.vclock, "worker_evicted",
                         sim_id=self.sim_id, worker_id=self.worker_id)

    # -- lease / report ------------------------------------------------ #

    def _lease(self, inc: int) -> None:
        if self._stale(inc) or not self.registered:
            return
        fleet = self.fleet
        sc = fleet.scenario
        req = pb.GetTaskRequest(
            worker_id=self.worker_id, max_tasks=sc.lease_batch)
        try:
            resp = fleet.rpc("GetTask", req,
                             [(GENERATION_KEY, str(self.generation))])
        except SimRpcError as e:
            if e.stale_generation:
                self._refence()
                return
            delay = self._next_backoff()
            self._pend_reconnect += delay
            fleet.sched.after(delay, lambda: self._lease(inc))
            return
        self._backoff = 0.0
        if resp.job_done:
            return   # keep beating; the heartbeat's job_done retires us
        tasks = list(resp.tasks) or [resp.task]
        if tasks[0].type == pb.WAIT:
            delay = max(0.05, resp.backoff_seconds) \
                * (0.9 + 0.2 * self.rng.random())
            self._pend_lease_wait += delay
            fleet.sched.after(delay, lambda: self._lease(inc))
            return
        fleet.stat["lease_batches"] += 1
        fleet.stat["leases_acked"] += len(tasks)
        self.held.extend(tasks)
        # work the batch sequentially at the scripted retire rate, then
        # lease again
        offset = 0.0
        rate = sc.records_per_s / self.straggle_factor
        for task in tasks:
            offset += max(task.end - task.start, 1) / rate
            self.fleet.sched.after(
                offset, lambda t=task: self._report(t, inc))
        fleet.sched.after(offset + 0.001, lambda: self._lease(inc))

    def _report(self, task, inc: int) -> None:
        if self._stale(inc):
            return
        fleet = self.fleet
        records = max(task.end - task.start, 1)
        req = pb.ReportTaskResultRequest(
            worker_id=self.worker_id, task_id=task.task_id, success=True,
            records_processed=records, model_version=self.steps,
            loss_sum=1.0, loss_count=1,
        )
        try:
            resp = fleet.rpc("ReportTaskResult", req,
                             [(GENERATION_KEY, str(self.generation))])
        except SimRpcError as e:
            if e.stale_generation:
                # completed work discarded by the fence (billed wasted
                # master-side via note_fenced_report); re-register and
                # re-lease — the replayed queue holds the requeued task
                self._refence()
                return
            delay = self._next_backoff()
            self._pend_reconnect += delay
            fleet.sched.after(delay, lambda: self._report(task, inc))
            return
        self._backoff = 0.0
        self.held = [t for t in self.held if t.task_id != task.task_id]
        if resp.accepted:
            fleet.stat["reports_acked"] += 1
            if task.type == pb.TRAINING:
                fleet.acked_training.add(task.task_id)
        else:
            fleet.stat["reports_rejected"] += 1


# --------------------------------------------------------------------- #
# the simulator-backed scale target


class SimScaleTarget:
    """The autoscaler's action surface, backed by the simulated fleet:
    grow provisions a brand-new scripted worker, shrink/evict route
    through the servicer's drain handshake (evict flag on the next
    heartbeat) — the same wire protocol production uses."""

    def __init__(self, fleet: "FleetSim"):
        self._fleet = fleet

    def world_size(self) -> int:
        return self._fleet.membership.alive_count()

    def supports(self, kind: str) -> bool:
        return True

    def grow(self) -> bool:
        self._fleet.spawn_worker()
        return True

    def shrink(self) -> bool:
        alive = [
            w.worker_id for w in self._fleet.membership.alive_workers()
            if w.led_by is None
        ]
        if not alive:
            return False
        return self.evict(max(alive))

    def evict(self, worker_id: int, worker_name: str = "") -> bool:
        self._fleet.servicer.request_evict(worker_id)
        self._fleet.events.log(
            self._fleet.vclock, "scale_evict_requested",
            worker_id=worker_id)
        return True


# --------------------------------------------------------------------- #
# the fleet simulator


class FleetSim:
    """One scenario run: build the real master, script the fleet,
    interpret the event schedule, and emit cliff metrics + incident
    artifacts. Single-threaded by design (determinism)."""

    def __init__(self, scenario: Scenario, workdir: str,
                 artifacts_dir: Optional[str] = None):
        self.scenario = scenario
        self.workdir = workdir
        self.artifacts_dir = artifacts_dir
        self.vclock = VirtualClock()
        self.sched = Scheduler(self.vclock)
        self.events = EventLog()
        self.rng = random.Random(scenario.seed)
        self.workers: List[SimWorker] = []
        self.master_down = False
        self.master_restarts = 0
        self.acked_training: set = set()
        self._members_of: Dict[int, List[int]] = {}
        self._eval_job_id = 0
        self._poll_active = False
        self._alert_onsets: List[Dict] = []
        self._as_totals = {"reversals": 0, "actions": {}, "suppressed": {}}
        self._ly_totals: Dict[str, Any] = {"actions": {}, "records": 0}
        self._flip: Optional[Dict[str, Any]] = None
        self._flip_count = 0
        self._phase_wall: Dict[str, List[float]] = {}
        self.stat = {
            k: 0 for k in (
                "registrations", "reregistrations", "heartbeats",
                "lease_batches", "leases_acked", "reports_acked",
                "reports_rejected", "fences_seen", "evictions_drained",
                "polls", "injected_tasks",
            )
        }
        # runtime lock-order recording across the whole run (restarts
        # union into one graph — the recorder is name-keyed, not
        # instance-keyed): any inversion the scenario drives the real
        # control plane into raises AT THE ACQUIRE, with both sites
        from elasticdl_tpu.analysis.lockorder import LockOrderRecorder

        self.lock_recorder = LockOrderRecorder(raise_on_cycle=True)
        # master-side handles, (re)bound by _build_master
        self.journal = None
        self.dispatcher = None
        self.membership = None
        self.servicer = None
        self.health = None
        self.goodput = None
        self.timeseries = None
        self.alerts = None
        self.autoscaler = None
        # embedding layout loop (ISSUE 20): the owner map + controller
        # rebuild on master restart (journal-restored); the stores stand
        # in for worker-side shard state and survive restarts like the
        # workers do
        self.emb_owner = None
        self.layout_ctl = None
        self.emb_stores: Dict[int, Any] = {}
        self.generation = 0

    # -- master build / kill / restart --------------------------------- #

    def _scaled_rules(self):
        import dataclasses

        from elasticdl_tpu.observability import alerts as alerts_lib

        scale = self.scenario.alert_window_scale
        rules = []
        for r in alerts_lib.default_rules():
            rules.append(dataclasses.replace(
                r,
                window_s=max(1.0, r.window_s * scale),
                long_window_s=(max(2.0, r.long_window_s * scale)
                               if r.long_window_s else 0.0),
                for_s=r.for_s * scale,
            ))
        return rules

    def _build_master(self) -> None:
        from elasticdl_tpu.master import autoscaler as autoscaler_lib
        from elasticdl_tpu.master.journal import ControlPlaneJournal
        from elasticdl_tpu.master.membership import Membership
        from elasticdl_tpu.master.servicer import MasterServicer
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.observability.alerts import AlertEngine
        from elasticdl_tpu.observability.goodput import FleetGoodput
        from elasticdl_tpu.observability.health import ClusterHealth
        from elasticdl_tpu.observability.timeseries import TimeSeriesStore

        sc = self.scenario
        if self.autoscaler is not None:
            self._harvest_autoscaler()
        if self.layout_ctl is not None:
            self._harvest_layout()
        self.journal = ControlPlaneJournal(
            self.workdir, group_commit_ms=sc.group_commit_ms)
        eval_shards = (
            [("sim-eval", 0, min(sc.eval_task_records, sc.records_per_task))]
            if sc.eval_task_records > 0 else None
        )
        self.dispatcher = TaskDispatcher(
            training_shards=[("sim-train", 0,
                              sc.shards * sc.records_per_task)],
            evaluation_shards=eval_shards,
            records_per_task=sc.records_per_task,
            num_epochs=sc.epochs,
            shuffle=False,
            task_timeout_s=sc.task_timeout_s,
            journal=self.journal,
            clock=self.vclock.now,
        )
        self.membership = Membership(
            heartbeat_timeout_s=sc.heartbeat_timeout_s,
            journal=self.journal,
            clock=self.vclock.now,
        )
        self.membership.add_death_callback(self.dispatcher.recover_tasks)
        self.servicer = MasterServicer(
            self.dispatcher, self.membership, None,
            wait_backoff_s=sc.wait_backoff_s,
            generation=self.journal.generation,
        )
        self.generation = self.journal.generation
        self.health = ClusterHealth(
            self.membership, min_workers=3,
            stale_after_s=3.0 * sc.heartbeat_s,
        )
        self.goodput = FleetGoodput(self.membership, self.dispatcher)
        self.timeseries = TimeSeriesStore(interval_s=sc.poll_s)
        self.alerts = AlertEngine(
            self.timeseries, rules=self._scaled_rules(),
            json_path=(os.path.join(self.artifacts_dir, "alerts.json")
                       if self.artifacts_dir else None),
            flight_dump=lambda reason: None,
        )
        self.alerts.add_hook(self._on_alert_onset)
        self.autoscaler = None
        if sc.autoscale:
            a = dict(sc.autoscale)
            self.autoscaler = autoscaler_lib.Autoscaler(
                journal=self.journal,
                cost_model=autoscaler_lib.CostModel(
                    rescale_cost_s=a.get("rescale_cost_s", 5.0),
                    horizon_s=a.get("horizon_s", 300.0),
                ),
                min_world=int(a.get("min_workers", 1)),
                max_world=int(a.get("max_workers", 0)),
                cooldown_s=a.get("cooldown_s", 30.0),
                hold_s=a.get("hold_s", 10.0),
                action_budget=int(a.get("actions_max", 8)),
                damping=a.get("damping", 0.0),
                reversal_hold_s=a.get("reversal_hold_s", 0.0),
                clock=self.vclock.now,
            )
            self.autoscaler.subscribe(health=self.health, alerts=self.alerts)
            self.autoscaler.bind_target(SimScaleTarget(self))
        self.emb_owner = None
        self.layout_ctl = None
        if sc.layout:
            self._build_layout(dict(sc.layout))
        from elasticdl_tpu.analysis.lockorder import instrument_master

        instrument_master(
            self.lock_recorder,
            membership=self.membership,
            dispatcher=self.dispatcher,
            servicer=self.servicer,
            journal=self.journal,
            autoscaler=self.autoscaler,
        )

    def _harvest_autoscaler(self) -> None:
        """Accumulate a dying autoscaler instance's per-run counters (a
        master restart rebuilds the instance; the run's totals must
        survive it). Reversals are in-memory-only → summed; by_kind is
        journal-durable (replayed into the successor) → overwritten."""
        snap = self.autoscaler.snapshot()
        self._as_totals["reversals"] += int(snap.get("reversals", 0))
        if snap.get("by_kind"):
            self._as_totals["actions"] = {
                k: int(v) for k, v in snap["by_kind"].items()
            }

    def _build_layout(self, ly: Dict[str, Any]) -> None:
        """The REAL layout stack on the virtual clock: a journaled
        ShardMapOwner, in-process stores standing in for the workers'
        shard state, and the layout controller subscribed to the same
        alert engine the flips drive. A master restart rebuilds owner
        and controller FROM THE JOURNAL (the takeover path under test);
        the stores persist like workers do."""
        from elasticdl_tpu.embedding.sharding import ShardMapOwner, TableSpec
        from elasticdl_tpu.embedding.store import EmbeddingShardStore
        from elasticdl_tpu.master import layout_controller as layout_lib

        n0 = int(ly.get("num_shards", 8))
        hosts = list(range(min(4, self.scenario.workers)))
        self.emb_owner = ShardMapOwner(n0, journal=self.journal)
        self.emb_owner.register_table(
            TableSpec("emb", vocab=max(256, 4 * n0), dim=8))
        restored = self.journal.embedding_snapshot()
        if restored is not None and restored.version > 0:
            self.emb_owner.restore_from_replay(restored)
        else:
            self.emb_owner.bootstrap(hosts)
        if not self.emb_stores:
            self.emb_stores = {h: EmbeddingShardStore(h) for h in hosts}
            for st in self.emb_stores.values():
                st.attach(self.emb_owner.view(), "")
        self.layout_ctl = layout_lib.LayoutController(
            journal=self.journal,
            cost_model=layout_lib.LayoutCostModel(
                migrate_cost_s=float(ly.get("migrate_cost_s", 0.05)),
                horizon_s=float(ly.get("horizon_s", 120.0)),
            ),
            max_shards=int(ly.get("max_shards", 4 * n0)),
            min_shards=int(ly.get("min_shards", 1)),
            max_replicas=int(ly.get("max_replicas", 2)),
            hot_k=int(ly.get("hot_k", 16)),
            cooldown_s=float(ly.get("cooldown_s", 20.0)),
            hold_s=float(ly.get("hold_s", 5.0)),
            action_budget=int(ly.get("actions_max", 16)),
            clock=self.vclock.now,
        )
        self.layout_ctl.subscribe(alerts=self.alerts)
        self.layout_ctl.bind_target(layout_lib.StoreLayoutTarget(
            self.emb_owner, self.emb_stores))

    def _harvest_layout(self) -> None:
        """Layout decision totals across master restarts. Both counters
        are journal-durable (replayed into the successor), so the
        latest instance's snapshot IS the running total."""
        snap = self.layout_ctl.snapshot()
        if snap.get("by_kind"):
            self._ly_totals["actions"] = {
                k: int(v) for k, v in snap["by_kind"].items()
            }
        self._ly_totals["records"] = max(
            int(self._ly_totals["records"]),
            int(snap.get("decision_records", 0)))

    def layout_emb_stats(self) -> Dict[str, Any]:
        """The flipped fleet's embedding telemetry, CLOSED-LOOP: load
        concentrates on the flip's hot shard, and the modelled
        imbalance / pull p99 / cache hit rate recover as the layout
        controller's own actions (fan-out, split, hot promotion) land
        on the live shard map — so the alert rules that armed the
        controller also clear because of it."""
        if self.emb_owner is None or self._flip is None:
            return {}
        f = self._flip
        v = self.emb_owner.view()
        n = v.num_shards
        hs = float(f["hot_share"])
        hot = int(f.get("hot_shard", 0)) % n
        # relief already won: each replica of the hot shard absorbs an
        # equal cut of its reads; a split spreads the hot id set over
        # the children
        fan = 1 + len(v.replicas_of(hot))
        spread = max(1.0, float(n) / float(f["base_shards"]))
        head = [int(f["ids_base"]) + i for i in range(8)]
        # promoted: the ultra-hot head is worker-replicated — most of
        # its reads never reach the owner shard again
        promoted = set(head) <= {int(i) for i in v.hot_ids}
        eff_hot = hs / (fan * spread)
        if promoted:
            eff_hot *= 0.3
        cold = (1.0 - hs) / n
        shares = [cold + (hs - eff_hot) / n for _ in range(n)]
        shares[hot] = cold + eff_hot + (hs - eff_hot) / n
        total = sum(shares) or 1.0
        imb = max(shares) * n / total
        raw_imb = (hs + cold) * n  # the no-relief skew, for scaling p99
        p99 = max(25.0, float(f["pull_p99_ms"]) * imb / max(raw_imb, 1e-9))
        hit = max(0.05, 1.0 - hs)
        if promoted:
            hit = min(0.95, hit + 0.6)
        stats: Dict[str, Any] = {
            "emb_hot_id_share": round(hs, 3),
            "emb_pull_p99_ms": round(p99, 1),
            "emb_cache_hit_rate": round(hit, 3),
            "emb_shard_imbalance": round(imb, 3),
        }
        loads = ",".join(
            str(int(round(100.0 * s / total))) for s in shares)
        if len(loads) <= 64:
            stats["emb_shard_loads"] = loads
        ids = ""
        for i in head:
            nxt = f"{ids},{i}" if ids else str(i)
            if len(nxt) > 64:
                break
            ids = nxt
        if ids:
            stats["emb_hot_ids"] = ids
        return stats

    def _on_alert_onset(self, info: Dict) -> None:
        self._alert_onsets.append({
            "at_s": round(self.vclock.offset, 3),
            "rule": str(info.get("rule")),
            "severity": str(info.get("severity", "")),
        })

    def kill_master(self, down_s: float) -> None:
        """SIGKILL-equivalent: the journal's queued unacked commits are
        dropped (abort), every in-flight protocol future answers
        UNAVAILABLE, and recovery is a REAL journal replay."""
        if self.master_down:
            return
        self.events.log(self.vclock, "master_killed", down_s=down_s)
        self.master_down = True
        self.journal.abort()
        self.sched.after(down_s, self._restart_master)

    def _restart_master(self) -> None:
        self.master_restarts += 1
        self._build_master()
        self.master_down = False
        if not self._poll_active:
            # the poll chain retires itself once the job looks done; the
            # restored dispatcher deliberately forgets terminal flags
            # (poke() re-derives them and re-fires callbacks), so the
            # successor needs its own chain or job-end never re-fires
            self._poll_active = True
            self.sched.after(self.scenario.poll_s, self._poll)
        self.events.log(self.vclock, "master_restarted",
                        generation=self.generation,
                        requeued=(self.journal.replay.dispatcher.requeued_leases
                                  if self.journal.replay
                                  and self.journal.replay.dispatcher else 0))

    # -- the wire ------------------------------------------------------ #

    def rpc(self, method: str, request, metadata=()):
        """One worker→master call over the simulated wire."""
        if self.master_down or self.servicer is None:
            raise SimRpcError(grpc.StatusCode.UNAVAILABLE, "master down")
        return getattr(self.servicer, method)(
            request, SimContext(metadata))

    def cohort_member_ids(self, leader_id: int) -> List[int]:
        if self.scenario.cohort_members <= 0:
            return []
        ids = self._members_of.get(leader_id)
        if ids is None:
            ids = sorted(
                w.worker_id
                for w in self.membership.alive_workers()
                if w.led_by == leader_id
            )
            self._members_of[leader_id] = ids
        return ids

    def spawn_worker(self) -> SimWorker:
        w = SimWorker(self, len(self.workers))
        self.workers.append(w)
        w.boot(PROVISION_DELAY_S + self.rng.random())
        self.events.log(self.vclock, "scale_grow_provisioned",
                        sim_id=w.sim_id)
        return w

    # -- the master poll loop ------------------------------------------ #

    def _poll(self) -> None:
        sc = self.scenario
        if not self.master_down:
            self.stat["polls"] += 1
            now = self.vclock.now()
            self._members_of.clear()
            self._timed_phase("membership", self.membership.reap)
            self._timed_phase("dispatcher", self.dispatcher.poke)
            self._timed_phase("health", lambda: self.health.update(now=now))
            self._timed_phase("goodput", lambda: self.goodput.update(now=now))
            self._timed_phase(
                "timeseries",
                lambda: self.timeseries.maybe_sample(
                    now=now, extra_fn=self._fleet_series))
            self._timed_phase("alerts", lambda: self.alerts.evaluate(now=now))
            if self.autoscaler is not None:
                self._timed_phase(
                    "autoscaler", lambda: self.autoscaler.evaluate(now=now))
            if self.layout_ctl is not None:
                self._timed_phase(
                    "layout",
                    lambda: self.layout_ctl.evaluate(
                        now=now,
                        workers=self.membership.health_snapshot()))
        if self.vclock.offset + sc.poll_s <= self.scenario.duration_s \
                and not self.dispatcher.finished():
            self.sched.after(sc.poll_s, self._poll)
        else:
            self._poll_active = False

    def _timed_phase(self, phase: str, fn: Callable[[], Any]) -> None:
        t0 = time.perf_counter()
        with poll_phase(phase):
            fn()
        self._phase_wall.setdefault(phase, []).append(
            time.perf_counter() - t0)

    def _fleet_series(self) -> Dict[str, float]:
        from elasticdl_tpu.observability.timeseries import fleet_series

        now = self.vclock.now()
        counts = self.dispatcher.counts()
        snap = self.health.snapshot(now=now)
        series = fleet_series(
            self.membership.health_snapshot(),
            straggler_count=snap.get("straggler_count", 0),
            todo_tasks=counts.get("todo", 0),
            alive_workers=self.membership.alive_count(),
            stale_after_s=3.0 * self.scenario.heartbeat_s,
            now=now,
        )
        series.update(self.goodput.series())
        return series

    # -- scenario event interpreters ----------------------------------- #

    def _schedule_events(self) -> None:
        for ev in self.scenario.events:
            action = ev["action"]
            if action == "stagger_joins":
                continue   # consumed by _boot_fleet
            self.sched.at(
                float(ev["at_s"]), lambda e=dict(ev): self._run_event(e))

    def _run_event(self, ev: Dict[str, Any]) -> None:
        action = ev["action"]
        self.events.log(self.vclock, "scenario_event", **ev)
        getattr(self, f"_ev_{action}")(ev)

    def _alive(self) -> List[SimWorker]:
        return [w for w in self.workers if w.alive]

    def _dead(self) -> List[SimWorker]:
        return [w for w in self.workers if not w.alive and not w.evicted]

    def _ev_kill_rack(self, ev) -> None:
        for w in self._alive():
            if w.rack == int(ev["rack"]):
                w.die()

    def _ev_rejoin_rack(self, ev) -> None:
        for w in self._dead():
            if w.rack == int(ev["rack"]):
                w.rejoin(self.rng.random() * 2.0)

    def _ev_kill_workers(self, ev) -> None:
        alive = self._alive()
        for w in self.rng.sample(alive, min(int(ev["count"]), len(alive))):
            w.die()

    def _ev_rejoin_workers(self, ev) -> None:
        dead = self._dead()
        for w in self.rng.sample(dead, min(int(ev["count"]), len(dead))):
            w.rejoin(self.rng.random() * 2.0)

    def _ev_rolling_restart(self, ev) -> None:
        batch = max(1, int(ev["batch"]))
        interval, down = float(ev["interval_s"]), float(ev["down_s"])
        fleet = [w for w in self.workers if not w.evicted]
        for k in range(0, len(fleet), batch):
            group = fleet[k:k + batch]
            delay = (k // batch) * interval

            def restart(group=group):
                for w in group:
                    w.die()
                    self.sched.after(down, lambda w=w: w.rejoin(0.0))

            self.sched.after(delay, restart)

    def _ev_straggle(self, ev) -> None:
        alive = self._alive()
        for w in self.rng.sample(alive, min(int(ev["count"]), len(alive))):
            w.straggle_factor = max(1.0, float(ev["factor"]))
            w.straggle_until = self.vclock.offset + float(ev["for_s"])

    def _ev_set_data_wait(self, ev) -> None:
        frac = min(0.95, max(0.0, float(ev["frac"])))
        targets = self._alive()
        if "count" in ev:
            targets = self.rng.sample(
                targets, min(int(ev["count"]), len(targets)))
        for w in targets:
            w.data_wait_frac = frac

    def _ev_popularity_flip(self, ev) -> None:
        targets = self._alive()
        if "count" in ev:
            targets = self.rng.sample(
                targets, min(int(ev["count"]), len(targets)))
        for w in targets:
            w.emb = {
                "emb_hot_id_share": float(ev["hot_share"]),
                "emb_pull_p99_ms": float(ev["pull_p99_ms"]),
                "emb_cache_hit_rate": max(
                    0.05, 1.0 - float(ev["hot_share"])),
            }
        if self.emb_owner is not None:
            # a NEW hot set every flip: fresh sketch head ids, load
            # re-concentrated on the flip's hot shard — whatever relief
            # the controller won for the LAST head is now mis-aimed,
            # which is exactly the adapt-or-page scenario under test
            self._flip_count += 1
            self._flip = {
                "hot_share": float(ev["hot_share"]),
                "pull_p99_ms": float(ev["pull_p99_ms"]),
                "hot_shard": int(ev.get("hot_shard", 0)),
                "ids_base": 1000 * self._flip_count,
                "base_shards": self.emb_owner.view().num_shards,
            }

    def _ev_inject_tasks(self, ev) -> None:
        if self.master_down:
            return
        n = 0
        for _ in range(int(ev["count"])):
            self._eval_job_id += 1
            n += self.dispatcher.create_evaluation_tasks(self._eval_job_id)
        self.stat["injected_tasks"] += n

    def _ev_kill_master(self, ev) -> None:
        self.kill_master(float(ev["down_s"]))

    # -- run ----------------------------------------------------------- #

    def _boot_fleet(self) -> None:
        sc = self.scenario
        stagger = next(
            (ev for ev in sc.events if ev["action"] == "stagger_joins"),
            None,
        )
        for i in range(sc.workers):
            w = SimWorker(self, i)
            self.workers.append(w)
            if stagger is not None:
                delay = float(stagger["at_s"]) \
                    + self.rng.random() * float(stagger["over_s"])
            else:
                delay = self.rng.random() * 0.25
            w.boot(delay)
        if stagger is not None:
            self.events.log(self.vclock, "scenario_event", **stagger)

    def run(self) -> Dict[str, Any]:
        sc = self.scenario
        trace_path = None
        if self.artifacts_dir:
            os.makedirs(self.artifacts_dir, exist_ok=True)
            trace_path = os.path.join(self.artifacts_dir, "trace.jsonl")
        # the whole run inside a scoped tracer capture: a fleet soak pushes
        # thousands of spans through the real master stack, and leaking
        # them into the process tracer leaves its bounded ring full (and
        # the sim's role on every later log line) for whoever runs next
        # in this process — e.g. the rest of a test suite
        with tracing.get_tracer().scoped(path=trace_path, role="sim-master",
                                         world_version=0):
            self._build_master()
            self._boot_fleet()
            self._schedule_events()
            self._poll_active = True
            self.sched.after(sc.poll_s, self._poll)
            wall0 = time.perf_counter()
            self.sched.run(until=sc.duration_s)
            wall = time.perf_counter() - wall0
            result = self._finish(wall)
            if self.artifacts_dir:
                self._emit_artifacts(result)
        return result

    # -- cliff metrics + verification ---------------------------------- #

    def _finish(self, wall: float) -> Dict[str, Any]:
        sc = self.scenario
        counts = self.dispatcher.counts()
        wasted = self.dispatcher.wasted_work()
        finished = self.dispatcher.finished()
        if self.autoscaler is not None:
            self._harvest_autoscaler()
        if self.layout_ctl is not None:
            self._harvest_layout()

        # journal saturation: a post-run direct probe measures
        # enqueue-to-durable latency in this group-commit mode, plus the
        # run's own high-water (offered commit rate vs flush throughput)
        probe: List[float] = []
        if not self.master_down:
            for _ in range(50):
                t0 = time.perf_counter()
                self.journal.append("world_version", version=0).wait()
                probe.append(time.perf_counter() - t0)
        probe.sort()

        replay = self._check_replay()
        # every scenario doubles as a lock-order soak: the recorder
        # already raised at any inverting acquire; this sweep catches
        # cycles whose edges came from DIFFERENT threads' stacks, and
        # the observed edges land in the result for the static-graph
        # superset cross-check (test_lock_order.py)
        self.lock_recorder.assert_no_cycles()
        lock_edges = sorted(self.lock_recorder.edges())
        phases = {}
        for phase, walls in sorted(self._phase_wall.items()):
            s = sorted(walls)
            phases[phase] = {
                "count": len(s),
                "p50_ms": round(1e3 * s[len(s) // 2], 4),
                "p99_ms": round(
                    1e3 * s[min(len(s) - 1, math.ceil(len(s) * 0.99) - 1)],
                    4),
                "total_ms": round(1e3 * sum(s), 2),
            }

        acked = len(self.acked_training)
        lost_acked = max(
            0, acked - int(replay["replayed"]["finished_training"]))
        result = {
            "scenario": sc.name,
            "seed": sc.seed,
            "workers_configured": sc.workers,
            "workers_total": len(self.workers),
            "workers_final_alive": self.membership.alive_count(),
            "virtual_duration_s": sc.duration_s,
            "wall_s": round(wall, 3),
            "time_compression": round(sc.duration_s / max(wall, 1e-9), 1),
            "job_finished": finished,
            "master_restarts": self.master_restarts,
            "tasks": dict(counts, **{
                "records_completed": wasted["records_completed"],
                "wasted_records": wasted["wasted_records"],
            }),
            "stat": dict(self.stat),
            "leases_per_s": round(
                self.stat["leases_acked"] / max(wall, 1e-9), 1),
            "journal": {
                "group_commit_ms": sc.group_commit_ms,
                "flush_probe_p50_ms": round(
                    1e3 * probe[len(probe) // 2], 3) if probe else None,
                "flush_probe_p99_ms": round(
                    1e3 * probe[min(len(probe) - 1,
                                    math.ceil(len(probe) * 0.99) - 1)],
                    3) if probe else None,
                "commit_queue_high_water":
                    self.journal.commit_queue_high_water,
            },
            "poll_phases": phases,
            "alerts": {
                "onsets": len(self._alert_onsets),
                "by_rule": self._count_by(
                    self._alert_onsets, "rule"),
            },
            "autoscale": {
                "enabled": self.autoscaler is not None,
                "reversals": self._as_totals["reversals"],
                "actions_by_kind": dict(self._as_totals["actions"]),
            },
            "layout": {
                "enabled": self.layout_ctl is not None,
                "actions_by_kind": dict(self._ly_totals["actions"]),
                "decision_records": int(self._ly_totals["records"]),
                "final_num_shards": (
                    self.emb_owner.view().num_shards
                    if self.emb_owner is not None else 0),
                "final_imbalance": (
                    self.layout_emb_stats().get("emb_shard_imbalance")
                    if self._flip is not None else None),
            },
            "lock_order": {
                "edges": [[a, b] for a, b in lock_edges],
                "violations": len(self.lock_recorder.violations()),
            },
            "replay": replay,
            "acked_training_reports": acked,
            "lost_acked_leases": lost_acked,
            "event_log_entries": len(self.events.entries),
            "event_log_digest": self.events.digest(),
        }
        return result

    @staticmethod
    def _count_by(entries: List[Dict], key: str) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in entries:
            out[e[key]] = out.get(e[key], 0) + 1
        return out

    def _check_replay(self) -> Dict[str, Any]:
        """Journal replay identity: re-reading the journal MUST rebuild
        exactly the live dispatcher's accounting — the soak's
        zero-lost-acked-leases proof."""
        from elasticdl_tpu.master.journal import replay_lines

        self.journal.close()
        with open(self.journal.path, encoding="utf-8") as f:
            lines = f.readlines()
        rr = replay_lines(lines)
        counts = self.dispatcher.counts()
        wasted = self.dispatcher.wasted_work()
        live = {
            "finished_training": counts["finished_training"],
            "failed_permanently": counts["failed_permanently"],
            "records_completed": wasted["records_completed"],
            "wasted_records": wasted["wasted_records"],
        }
        d = rr.dispatcher
        replayed = {
            "finished_training": d.finished_training if d else 0,
            "failed_permanently": d.failed_permanently if d else 0,
            "records_completed": d.records_completed if d else 0,
            "wasted_records": d.wasted_records if d else 0,
        }
        layout_replay = None
        if self.layout_ctl is not None:
            # the layout proof: re-reading the journal rebuilds the FULL
            # decision history (applied + suppressed, per-kind counters)
            # the live controller carries — the takeover never forgets
            # or double-counts a decision
            ly_live = {
                "by_kind": {k: int(v) for k, v
                            in self._ly_totals["actions"].items()},
                "records": int(self._ly_totals["records"]),
            }
            lyr = rr.layout
            ly_replayed = {
                "by_kind": ({k: int(v) for k, v in lyr.by_kind.items()}
                            if lyr else {}),
                "records": lyr.records if lyr else 0,
            }
            layout_replay = {
                "identical": ly_live == ly_replayed,
                "live": ly_live,
                "replayed": ly_replayed,
            }
        return {
            "identical": live == replayed and (
                layout_replay is None or layout_replay["identical"]),
            "live": live,
            "replayed": replayed,
            **({"layout": layout_replay} if layout_replay else {}),
            "journal_records": rr.records,
            "dropped_lines": rr.dropped_lines,
        }

    # -- artifacts ----------------------------------------------------- #

    def _emit_artifacts(self, result: Dict[str, Any]) -> None:
        """The incident CLI's input set: journal copy, health snapshot,
        alerts state (already written by the engine), trace, the event
        log, and the result."""
        from elasticdl_tpu.observability import tracing

        adir = self.artifacts_dir
        shutil.copyfile(
            self.journal.path, os.path.join(adir, "journal.jsonl"))
        now = self.vclock.now()

        def _dump(name: str, doc: Any, **kw: Any) -> None:
            path = os.path.join(adir, name)
            with open(path + ".tmp", "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2, **kw)
            os.replace(path + ".tmp", path)

        _dump("health.json", {
            "cluster": self.health.snapshot(now=now),
            "goodput": self.goodput.snapshot(),
            "alerts": self.alerts.snapshot(),
        }, sort_keys=True, default=repr)
        _dump("events.json", self.events.entries)
        tracing.get_tracer().close()
        _dump("result.json", result, sort_keys=True)
        result["incident_strict_rc"] = self._incident_check(adir)

    @staticmethod
    def _incident_check(adir: str) -> int:
        """`python -m elasticdl_tpu.observability.incident <dir> --strict`
        over the run's artifacts; report text lands next to them."""
        from elasticdl_tpu.observability import incident

        report = os.path.join(adir, "incident_report.txt")
        with open(report, "w", encoding="utf-8") as out, \
                redirect_stdout(out):
            return incident.main([adir, "--strict"])


def run_scenario(scenario: Scenario, workdir: str,
                 artifacts_dir: Optional[str] = None) -> Dict[str, Any]:
    """Convenience wrapper: one FleetSim run."""
    sim = FleetSim(scenario, workdir, artifacts_dir=artifacts_dir)
    try:
        return sim.run()
    finally:
        try:
            if sim.journal is not None:
                sim.journal.close()
        except Exception:
            logger.debug("journal close after run failed", exc_info=True)
