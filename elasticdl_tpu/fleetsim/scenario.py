"""Scenario schema: the fleet soak's *data, not code* contract.

A scenario is one JSON object — a seeded, replayable schedule of fleet
events over compressed VIRTUAL time. The same file + the same seed
produces the identical event log on every run (the determinism the
fleetsim tests pin), so a cliff found at 03:00 in CI replays exactly on
a laptop. Schema (see docs/soak.md for the annotated example):

```
{
  "name": "rack_failure",            // required, [a-z0-9_]+
  "description": "...",
  "seed": 42,                        // every RNG in the run derives here
  "duration_s": 600,                 // VIRTUAL seconds simulated
  "workers": 1000,                   // simulated logical workers
  "racks": 16,                       // workers round-robin onto racks
  "cohort_members": 0,               // member processes per worker
  "poll_s": 1.0,                     // master wait-poll cadence (virtual)
  "heartbeat_s": 10.0,               // worker beat period (virtual)
  "heartbeat_timeout_s": 30.0,
  "task_timeout_s": 120.0,
  "shards": 2000,                    // training shards (1 task each)
  "records_per_task": 4096,
  "epochs": 1,                       // dispatcher epochs (a small shard
                                     // set x many epochs = a steady
                                     // backdrop that never drains todo)
  "eval_task_records": 0,            // records per injected eval task
                                     // (0 = inject_tasks unavailable)
  "lease_batch": 4,                  // max_tasks per GetTask
  "step_ms": 100.0,                  // baseline per-step wall
  "records_per_s": 40000.0,          // per-worker retire rate
  "data_wait_frac": 0.05,            // baseline input-blocked fraction
  "group_commit_ms": 2.0,            // journal window (REAL ms)
  "wait_backoff_s": 2.0,
  "alert_window_scale": 1.0,         // shrink alert windows to match
                                     // the compressed timescale
  "autoscale": null | {              // omit/null = loop off
    "min_workers", "max_workers", "cooldown_s", "hold_s",
    "actions_max", "rescale_cost_s", "horizon_s",
    "damping", "reversal_hold_s"
  },
  "layout": null | {                 // omit/null = layout loop off
    "num_shards", "max_shards", "min_shards", "max_replicas",
    "hot_k", "cooldown_s", "hold_s", "actions_max",
    "migrate_cost_s", "horizon_s"
  },
  "events": [ {"at_s": 120, "action": "kill_rack", "rack": 3}, ... ]
}
```

Event actions (each validated against REQUIRED_EVENT_FIELDS):

- ``kill_rack {rack}`` / ``rejoin_rack {rack}`` — correlated failure:
  every worker on the rack dies (stops beating, mid-lease) / reboots.
- ``kill_workers {count}`` / ``rejoin_workers {count}`` — seeded-random
  uncorrelated death/revival waves.
- ``rolling_restart {batch, interval_s, down_s}`` — restart the fleet
  `batch` workers at a time, each down `down_s`.
- ``stagger_joins {over_s}`` — slow-joiner herd: initial registration
  spread over a window instead of t=0.
- ``straggle {count, factor, for_s}`` — seeded-random workers run
  `factor`× slower for a while (honest step quantiles follow).
- ``set_data_wait {frac, count?}`` — flip (part of) the fleet's
  input-blocked fraction; drives the shrink alert.
- ``popularity_flip {hot_share, pull_p99_ms, count?, hot_shard?}`` —
  embedding hot set migrates: payloads carry the new hot-id share /
  pull p99 so the embedding alert rules see it. With a ``layout``
  block, payloads additionally carry the per-shard load shares and
  sketch head (``emb_shard_loads`` / ``emb_hot_ids``) the layout
  controller aggregates — concentrated on ``hot_shard`` (default 0) —
  and the modelled imbalance/p99/hit-rate RECOVER as the controller's
  fan-out/split actions take effect, closing the loop.
- ``inject_tasks {count}`` — burst of evaluation tasks into the real
  dispatcher (the backlog / grow-alert driver). Each task carries
  ``eval_task_records`` records, so burst-drain time is tunable
  independently of the training backdrop.
- ``kill_master {down_s}`` — SIGKILL-equivalent master death under
  load (journal aborted, queued unacked commits lost), then a real
  replay-recovery restart; workers reconnect through the
  generation-fence → re-register handshake.

Virtual-time semantics: ``at_s``/durations are virtual seconds; the
scheduler jumps the clock between events so a 10-minute soak runs in
seconds of wall. All REAL costs (journal fsync, lock passes, poll-phase
wall) are measured in real time — that is the point of the harness.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: bounded action vocabulary; {action: required numeric fields}
REQUIRED_EVENT_FIELDS: Dict[str, tuple] = {
    "kill_rack": ("rack",),
    "rejoin_rack": ("rack",),
    "kill_workers": ("count",),
    "rejoin_workers": ("count",),
    "rolling_restart": ("batch", "interval_s", "down_s"),
    "stagger_joins": ("over_s",),
    "straggle": ("count", "factor", "for_s"),
    "set_data_wait": ("frac",),
    "popularity_flip": ("hot_share", "pull_p99_ms"),
    "inject_tasks": ("count",),
    "kill_master": ("down_s",),
}

_AUTOSCALE_KEYS = {
    "min_workers", "max_workers", "cooldown_s", "hold_s", "actions_max",
    "rescale_cost_s", "horizon_s", "damping", "reversal_hold_s",
}

_LAYOUT_KEYS = {
    "num_shards", "max_shards", "min_shards", "max_replicas", "hot_k",
    "cooldown_s", "hold_s", "actions_max", "migrate_cost_s", "horizon_s",
}


@dataclass
class Scenario:
    name: str
    description: str = ""
    seed: int = 0
    duration_s: float = 600.0
    workers: int = 64
    racks: int = 8
    cohort_members: int = 0
    poll_s: float = 1.0
    heartbeat_s: float = 10.0
    heartbeat_timeout_s: float = 30.0
    task_timeout_s: float = 120.0
    shards: int = 256
    records_per_task: int = 4096
    epochs: int = 1
    eval_task_records: int = 0
    lease_batch: int = 4
    step_ms: float = 100.0
    records_per_s: float = 40000.0
    data_wait_frac: float = 0.05
    group_commit_ms: float = 2.0
    wait_backoff_s: float = 2.0
    alert_window_scale: float = 1.0
    autoscale: Optional[Dict[str, float]] = None
    layout: Optional[Dict[str, float]] = None
    events: List[Dict[str, Any]] = field(default_factory=list)

    def override(self, **kw) -> "Scenario":
        """A copy with fields replaced (the bench's undamped-twin and
        CI fleet-size knobs). `autoscale` overrides MERGE into the
        scenario's autoscale block. The copy re-runs the full schema
        validation, so an override can't mint a scenario that
        load_scenario would have rejected."""
        import dataclasses

        merged = dict(kw)
        if "autoscale" in merged and self.autoscale is not None \
                and merged["autoscale"] is not None:
            base = dict(self.autoscale)
            base.update(merged["autoscale"])
            merged["autoscale"] = base
        if "layout" in merged and self.layout is not None \
                and merged["layout"] is not None:
            base = dict(self.layout)
            base.update(merged["layout"])
            merged["layout"] = base
        out = dataclasses.replace(self, **merged)
        return validate_scenario(dataclasses.asdict(out))


def _fail(name: str, msg: str) -> ValueError:
    return ValueError(f"scenario {name!r}: {msg}")


def validate_scenario(raw: Dict[str, Any]) -> Scenario:
    """Dict → Scenario, or a ValueError that names the offending field —
    a scenario is config handed to a 1000-worker soak, and a typo must
    fail at load, not 400 virtual seconds in."""
    if not isinstance(raw, dict):
        raise ValueError("scenario must be a JSON object")
    name = str(raw.get("name") or "")
    if not name or not all(c.isalnum() or c == "_" for c in name):
        raise ValueError(
            f"scenario name {name!r} must be non-empty [a-zA-Z0-9_]+")
    known = {f.name for f in Scenario.__dataclass_fields__.values()}
    unknown = set(raw) - known
    if unknown:
        raise _fail(name, f"unknown field(s) {sorted(unknown)}")
    sc = Scenario(name=name, **{
        k: v for k, v in raw.items() if k != "name"
    })
    if sc.workers < 1:
        raise _fail(name, "workers must be >= 1")
    if sc.racks < 1:
        raise _fail(name, "racks must be >= 1")
    if sc.duration_s <= 0:
        raise _fail(name, "duration_s must be > 0")
    if sc.poll_s <= 0 or sc.heartbeat_s <= 0:
        raise _fail(name, "poll_s and heartbeat_s must be > 0")
    if sc.heartbeat_timeout_s <= sc.heartbeat_s:
        raise _fail(name, "heartbeat_timeout_s must exceed heartbeat_s")
    if sc.shards < 0 or sc.eval_task_records < 0:
        raise _fail(name, "shards/eval_task_records must be >= 0")
    if sc.epochs < 1:
        raise _fail(name, "epochs must be >= 1")
    if sc.lease_batch < 1:
        raise _fail(name, "lease_batch must be >= 1")
    if sc.records_per_s <= 0:
        raise _fail(name, "records_per_s must be > 0")
    if not 0.0 <= sc.data_wait_frac < 1.0:
        raise _fail(name, "data_wait_frac must be in [0, 1)")
    if sc.alert_window_scale <= 0:
        raise _fail(name, "alert_window_scale must be > 0")
    if sc.autoscale is not None:
        if not isinstance(sc.autoscale, dict):
            raise _fail(name, "autoscale must be an object or null")
        bad = set(sc.autoscale) - _AUTOSCALE_KEYS
        if bad:
            raise _fail(name, f"unknown autoscale key(s) {sorted(bad)}")
    if sc.layout is not None:
        if not isinstance(sc.layout, dict):
            raise _fail(name, "layout must be an object or null")
        bad = set(sc.layout) - _LAYOUT_KEYS
        if bad:
            raise _fail(name, f"unknown layout key(s) {sorted(bad)}")
        if int(sc.layout.get("num_shards", 8)) < 1:
            raise _fail(name, "layout.num_shards must be >= 1")
    for i, ev in enumerate(sc.events):
        if not isinstance(ev, dict):
            raise _fail(name, f"events[{i}] must be an object")
        action = ev.get("action")
        if action not in REQUIRED_EVENT_FIELDS:
            raise _fail(
                name,
                f"events[{i}] action {action!r} not in "
                f"{sorted(REQUIRED_EVENT_FIELDS)}")
        at = ev.get("at_s")
        if not isinstance(at, (int, float)) or at < 0:
            raise _fail(name, f"events[{i}] needs numeric at_s >= 0")
        if at > sc.duration_s:
            raise _fail(
                name, f"events[{i}] at_s {at} is past duration_s "
                      f"{sc.duration_s}")
        if action == "inject_tasks" and sc.eval_task_records < 1:
            raise _fail(
                name,
                f"events[{i}] inject_tasks needs eval_task_records >= 1")
        for fld in REQUIRED_EVENT_FIELDS[action]:
            if not isinstance(ev.get(fld), (int, float)):
                raise _fail(
                    name,
                    f"events[{i}] ({action}) needs numeric field "
                    f"{fld!r}")
    return sc


def load_scenario(path: str) -> Scenario:
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    return validate_scenario(raw)


_SCENARIO_DIR = os.path.join(os.path.dirname(__file__), "scenarios")


def builtin_scenarios() -> List[str]:
    """Names of the committed scenario library."""
    return sorted(
        fn[:-5] for fn in os.listdir(_SCENARIO_DIR) if fn.endswith(".json")
    )


def builtin_scenario_path(name: str) -> str:
    path = os.path.join(_SCENARIO_DIR, f"{name}.json")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no builtin scenario {name!r}; have {builtin_scenarios()}")
    return path
