"""Protocol-faithful fleet simulator (ISSUE 16; ROADMAP 6).

Thousands of scripted per-worker lifecycles — register → heartbeat /
member-beats → lease batches → report → die/rejoin, with honest stats
payloads — driven against the REAL master control plane: real journal
with group-commit, real membership, real dispatcher, real alert engine,
real autoscaler behind a simulator-backed scale target. Scenarios are
data, not code (scenario.py): a seeded, replayable JSON schedule over
compressed virtual time, interpreted by a deterministic single-threaded
scheduler (sim.py).

Entry points: ``python -m elasticdl_tpu.fleetsim <scenario.json>`` and
``bench.py fleet_soak``. See docs/soak.md.
"""

from elasticdl_tpu.fleetsim.scenario import (  # noqa: F401
    Scenario, load_scenario, builtin_scenario_path, builtin_scenarios,
)
from elasticdl_tpu.fleetsim.sim import FleetSim, SimRpcError  # noqa: F401
