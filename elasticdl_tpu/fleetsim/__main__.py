"""CLI: run one fleet-soak scenario against the real master.

    python -m elasticdl_tpu.fleetsim <scenario.json | builtin-name> \
        [--workers N] [--seed S] [--duration-s D] \
        [--artifacts DIR] [--json] [--list]

Exit code: 0 when the run is clean — job accounting replays
record-identically, zero lost acked leases, and (with --artifacts) the
incident CLI's --strict pass over the run's artifacts returns 0 —
else 1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

from elasticdl_tpu.fleetsim.scenario import (
    builtin_scenario_path, builtin_scenarios, load_scenario,
)
from elasticdl_tpu.fleetsim.sim import run_scenario


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.fleetsim",
        description="scenario-driven fleet soak against the real master",
    )
    parser.add_argument(
        "scenario", nargs="?",
        help="scenario JSON path, or a builtin name (see --list)")
    parser.add_argument("--list", action="store_true",
                        help="list builtin scenarios and exit")
    parser.add_argument("--workers", type=int, default=0,
                        help="override the scenario's fleet size")
    parser.add_argument("--seed", type=int, default=None,
                        help="override the scenario's seed")
    parser.add_argument("--duration-s", type=float, default=0.0,
                        help="override the scenario's virtual duration")
    parser.add_argument("--artifacts", default="",
                        help="emit incident artifacts (journal, health, "
                             "alerts, trace, event log) into this dir and "
                             "run the incident CLI --strict over them")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="print the full result JSON")
    args = parser.parse_args(argv)

    if args.list:
        for name in builtin_scenarios():
            print(name)
        return 0
    if not args.scenario:
        parser.error("scenario required (or --list)")

    path = args.scenario
    if not os.path.exists(path):
        path = builtin_scenario_path(args.scenario)
    sc = load_scenario(path)
    overrides = {}
    if args.workers > 0:
        overrides["workers"] = args.workers
    if args.seed is not None:
        overrides["seed"] = args.seed
    if args.duration_s > 0:
        overrides["duration_s"] = args.duration_s
    if overrides:
        sc = sc.override(**overrides)

    with tempfile.TemporaryDirectory(prefix="fleetsim-") as tmp:
        result = run_scenario(
            sc, tmp, artifacts_dir=args.artifacts or None)

    if args.as_json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        print(
            f"{result['scenario']}: {result['workers_total']} workers, "
            f"{result['virtual_duration_s']:.0f} virtual s in "
            f"{result['wall_s']:.1f}s wall "
            f"({result['time_compression']:.0f}x)"
        )
        print(
            f"  leases/s {result['leases_per_s']:.0f}  "
            f"journal flush p99 "
            f"{result['journal']['flush_probe_p99_ms']}ms  "
            f"queue high-water "
            f"{result['journal']['commit_queue_high_water']}"
        )
        print(
            f"  replay identical: {result['replay']['identical']}  "
            f"lost acked leases: {result['lost_acked_leases']}  "
            f"autoscale reversals: {result['autoscale']['reversals']}"
        )

    ok = result["replay"]["identical"] and result["lost_acked_leases"] == 0
    if args.artifacts:
        ok = ok and result.get("incident_strict_rc") == 0
    if not ok:
        print("fleet soak FAILED the clean-run contract", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
