"""User-facing layers — THE key abstraction being `Embedding`.

Reference parity: `elasticdl.layers.Embedding`
(elasticdl/python/elasticdl/layers/embedding.py) — a Keras layer that pulls
only the touched rows from the parameter-server tier per batch and pushes
per-id sparse gradients back. Here the table is a mesh-sharded `jax.Array`
param living in HBM; lookup + gradient scatter-add are ICI collectives inside
the jitted step (see elasticdl_tpu/ops/embedding.py). The layer is
mesh-agnostic: its partitioning metadata names every ambient mesh axis at
init time, so the same model runs on a 1-D ("data",) or 2-D ("data","model")
mesh unchanged.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from elasticdl_tpu.ops import embedding as emb_ops


class Embedding(nn.Module):
    """Mesh-sharded embedding with optional bag combiner.

    input_dim: vocabulary size (rows are padded to emb_ops.VOCAB_ALIGN so any
      mesh up to that many shards divides the table evenly).
    output_dim: embedding dimension.
    combiner: None → (..., L, D); 'sum'|'mean'|'sqrtn' → (..., D) over the
      last id axis, with negative ids treated as padding slots.
    mode: 'manual' (explicit shard_map collectives) or 'auto' (XLA GSPMD).
    """

    input_dim: int
    output_dim: int
    combiner: Optional[str] = None
    mode: str = "manual"
    embeddings_initializer: Callable = nn.initializers.uniform(scale=0.05)
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids: jax.Array, weights: Optional[jax.Array] = None):
        rows = emb_ops.padded_vocab(self.input_dim)
        axes = emb_ops.table_partition_axes()
        table = self.param(
            "table",
            nn.with_partitioning(
                self.embeddings_initializer, (axes if axes else None, None)
            ),
            (rows, self.output_dim),
            self.param_dtype,
        )
        ids = jnp.asarray(ids, jnp.int32)
        vectors = emb_ops.embedding_lookup(table, ids, mode=self.mode)
        return emb_ops.combine(vectors, self.combiner, ids, weights)
