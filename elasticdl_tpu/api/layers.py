"""User-facing layers — THE key abstraction being `Embedding`.

Reference parity: `elasticdl.layers.Embedding`
(elasticdl/python/elasticdl/layers/embedding.py) — a Keras layer that pulls
only the touched rows from the parameter-server tier per batch and pushes
per-id sparse gradients back. Here the table is a mesh-sharded `jax.Array`
param living in HBM; lookup + gradient scatter-add are ICI collectives inside
the jitted step (see elasticdl_tpu/ops/embedding.py). The layer is
mesh-agnostic: its partitioning metadata names every ambient mesh axis at
init time, so the same model runs on a 1-D ("data",) or 2-D ("data","model")
mesh unchanged.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from elasticdl_tpu.ops import embedding as emb_ops


class Embedding(nn.Module):
    """Mesh-sharded embedding with optional bag combiner.

    input_dim: vocabulary size (rows are padded via emb_ops.padded_vocab:
      to VOCAB_ALIGN=256 for even mesh shards, or to 8192 for vocabs >=
      PALLAS_VOCAB_MIN so the Pallas placement kernel emits whole blocks
      — the padded row count is part of the checkpoint geometry).
    output_dim: embedding dimension.
    combiner: None → (..., L, D); 'sum'|'mean'|'sqrtn' → (..., D) over the
      last id axis, with negative ids treated as padding slots.
    mode: 'manual' (explicit shard_map collectives) or 'auto' (XLA GSPMD).
    vocab_align: override the padding alignment (None = the current rule).
      Restoring a checkpoint written under an older padding rule requires
      rebuilding the model with ITS alignment — e.g. vocab_align=256 for
      large-vocab checkpoints from before the round-5 8192 alignment
      (CheckpointManager's restore error names the value to pass).
    """

    input_dim: int
    output_dim: int
    combiner: Optional[str] = None
    mode: str = "manual"
    embeddings_initializer: Callable = nn.initializers.uniform(scale=0.05)
    param_dtype: jnp.dtype = jnp.float32
    vocab_align: Optional[int] = None

    @nn.compact
    def __call__(self, ids: jax.Array, weights: Optional[jax.Array] = None):
        rows = emb_ops.padded_vocab(self.input_dim, self.vocab_align)
        axes = emb_ops.table_partition_axes()
        table = self.param(
            "table",
            nn.with_partitioning(
                self.embeddings_initializer, (axes if axes else None, None)
            ),
            (rows, self.output_dim),
            self.param_dtype,
        )
        ids = jnp.asarray(ids, jnp.int32)
        vectors = emb_ops.embedding_lookup(table, ids, mode=self.mode)
        return emb_ops.combine(vectors, self.combiner, ids, weights)


class TierEmbedding(nn.Module):
    """Embedding served from the elastic sharded tier
    (elasticdl_tpu/embedding/) instead of a mesh-sharded HBM param — the
    routing for tables too large for any single host's memory.

    The tier's pull happens OUTSIDE the jitted step (the table is not a
    model param at all): the worker's EmbeddingTierSession dedupes the
    batch's ids, pulls one batched call per owning shard, and feeds the
    (B, ..., L, D) `vectors` in as a jit INPUT; this layer applies the
    same combiner/padding semantics as `Embedding`. The gradient w.r.t.
    `vectors` — which jax gives for free since they are an input — is
    exactly the sparse per-row gradient the session pushes back (deduped
    scatter-add on the owning shard, reference parity with
    elasticdl.layers.Embedding's pull/push contract).

    `Embedding.as_tier_spec()` converts an existing in-HBM Embedding's
    geometry into the TableSpec the tier registers.
    """

    output_dim: int
    combiner: Optional[str] = None

    @nn.compact
    def __call__(self, vectors: jax.Array, ids: jax.Array,
                 weights: Optional[jax.Array] = None,
                 inverse: Optional[jax.Array] = None):
        ids = jnp.asarray(ids, jnp.int32)
        if inverse is not None:
            # deduped-end-to-end shape (EmbeddingTierClient.pull_unique):
            # `vectors` holds one row per UNIQUE id and `inverse` maps
            # batch slots onto them — the expansion gather runs here, on
            # device, and autodiff through it hands the session back
            # per-unique-row gradients, already duplicate-summed
            vectors = jnp.take(vectors, inverse, axis=0)
        return emb_ops.combine(vectors, self.combiner, ids, weights)


def tier_table_spec(name: str, input_dim: int, output_dim: int,
                    seed: int = 0, init_scale: float = 0.05):
    """The tier TableSpec matching an `Embedding(input_dim, output_dim)`
    layer's geometry: rows padded by the SAME rule as the in-HBM path
    (ops/embedding.padded_vocab), so a model can switch between HBM and
    tier routing without changing its checkpointed geometry story."""
    from elasticdl_tpu.embedding.sharding import TableSpec

    return TableSpec(
        name=name,
        vocab=emb_ops.padded_vocab(input_dim),
        dim=output_dim,
        seed=seed,
        init_scale=init_scale,
    )


class MoE(nn.Module):
    """Switch-style top-1 Mixture-of-Experts FFN with expert parallelism.

    Expert weights are stacked (num_experts, ...) and sharded one group
    per shard of the ambient mesh's `expert` axis (mesh-adaptive — on a
    mesh without one the experts replicate and the layer still works);
    token dispatch lowers to all_to_all via GSPMD (ops/moe.py). Output is
    residual: over-capacity tokens pass through unchanged. The Switch
    load-balancing aux loss is sown into the "losses" collection
    (`moe_aux`) for callers that thread mutable collections; with an
    immutable apply the sow is a no-op and routing still works, just
    without the balance penalty.
    """

    num_experts: int
    hidden_dim: int
    capacity_factor: float = 1.25
    kernel_init: Callable = nn.initializers.normal(0.02)
    # residual=False returns only the expert mix (dropped tokens -> 0) for
    # callers that add their own residual (pre-norm transformer blocks)
    residual: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from elasticdl_tpu.ops import moe as moe_ops

        c = x.shape[-1]
        e, h = self.num_experts, self.hidden_dim
        names = moe_ops.expert_partition_names
        wg = self.param("router", self.kernel_init, (c, e), jnp.float32)
        w1 = self.param(
            "w1", nn.with_partitioning(self.kernel_init, names(3)),
            (e, c, h), jnp.float32)
        b1 = self.param(
            "b1", nn.with_partitioning(nn.initializers.zeros, names(2)),
            (e, h), jnp.float32)
        w2 = self.param(
            "w2", nn.with_partitioning(self.kernel_init, names(3)),
            (e, h, c), jnp.float32)
        b2 = self.param(
            "b2", nn.with_partitioning(nn.initializers.zeros, names(2)),
            (e, c), jnp.float32)
        flat = x.reshape(-1, c)
        out, aux = moe_ops.switch_moe(
            flat, wg, w1, b1, w2, b2, self.capacity_factor)
        # OVERWRITE semantics, not flax's default tuple-append: the trainer
        # threads mutable collections through every step, and an appending
        # sow would grow the pytree each step — changing its structure and
        # forcing a full retrace/recompile per train step (review-caught,
        # empirically confirmed)
        self.sow(
            "losses", "moe_aux", aux,
            reduce_fn=lambda prev, new: new,
            init_fn=lambda: jnp.float32(0.0),
        )
        out = out.reshape(x.shape)
        return x + out if self.residual else out
