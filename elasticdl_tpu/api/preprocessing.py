"""Feature preprocessing — the elasticdl_preprocessing equivalent.

Reference parity: elasticdl_preprocessing/layers/*.py (Hashing, IndexLookup,
Discretization, Normalizer, ConcatenateWithOffset, ToSparse/ToRagged) used by
the census/deepfm zoo models.

TPU-first split: XLA cannot process strings, so preprocessing is split into
- HOST side (runs in the data pipeline, numpy): string hashing/lookup,
  ragged→padded-dense conversion;
- DEVICE side (jit-friendly jnp ops, usable inside models): integer hashing,
  bucketization, normalization, id-space concatenation with offsets.
The reference ran everything in the TF graph; here the host half runs once in
the input pipeline where it belongs, and the device half fuses into the step.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------- #
# Device-side (jit-friendly)


def hash_bucket(ids, num_bins: int):
    """Deterministic integer hash → [0, num_bins). Fibonacci/Knuth
    multiplicative hashing — one multiply + shift, VPU-friendly.

    Reference parity: Hashing layer (hash trick for unbounded vocabularies;
    the same trick that bounds the PS embedding table's key space).
    """
    x = jnp.asarray(ids, jnp.uint32)
    x ^= x >> 16
    x = x * jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x = x * jnp.uint32(0xC2B2AE35)
    x ^= x >> 16
    return (x % jnp.uint32(num_bins)).astype(jnp.int32)


def bucketize(values, boundaries: Sequence[float]):
    """Discretization: continuous → bucket id in [0, len(boundaries)]."""
    b = jnp.asarray(np.asarray(boundaries, np.float32))
    return jnp.searchsorted(b, jnp.asarray(values, jnp.float32), side="right").astype(
        jnp.int32
    )


def normalize(values, mean, std):
    """Standard-score normalization with fixed statistics."""
    return (jnp.asarray(values, jnp.float32) - mean) / jnp.maximum(std, 1e-12)


def multi_hot(ids, num_classes: int, weights=None):
    """Multi-hot / count encoding of an id bag: (..., L) int ids →
    (..., num_classes) float32, with negative ids (padding slots) skipped.

    Reference parity: the CategoryEncoding-style layer (binary/count
    output modes collapse to this plus an optional clip). Built as a
    one-hot sum so XLA keeps it fused — no scatter in the hot path —
    which is fine at preprocessing vocabulary sizes (<= a few thousand
    classes; use an Embedding table beyond that).
    """
    ids = jnp.asarray(ids, jnp.int32)
    # one_hot already zero-encodes negative/out-of-range ids, which is
    # exactly the padding-skip this needs — no extra mask
    oh = jax.nn.one_hot(ids, num_classes, dtype=jnp.float32)
    if weights is not None:
        oh = oh * jnp.asarray(weights, jnp.float32)[..., None]
    return jnp.sum(oh, axis=-2)


def log_normalize(values):
    """log(1+x) squashing — the standard Criteo dense-feature transform."""
    v = jnp.asarray(values, jnp.float32)
    return jnp.log1p(jnp.maximum(v, 0.0))


def concat_with_offset(id_groups: Sequence[jax.Array], sizes: Sequence[int]):
    """Concatenate per-feature id spaces into one shared table's id space.

    Reference parity: ConcatenateWithOffset — feature f's ids shift by
    sum(sizes[:f]) so one sharded table serves all features. Negative
    (padding) ids stay negative. Returns ids shaped (..., sum of group widths).
    """
    if len(id_groups) != len(sizes):
        raise ValueError("id_groups and sizes must align")
    out = []
    offset = 0
    for ids, size in zip(id_groups, sizes):
        ids = jnp.asarray(ids, jnp.int32)
        out.append(jnp.where(ids >= 0, ids + offset, ids))
        offset += int(size)
    return jnp.concatenate([o.reshape(o.shape[0], -1) for o in out], axis=-1)


def int_lookup(values, vocab: Sequence[int], num_oov: int = 1):
    """Device-side IndexLookup over a static integer vocabulary.

    Maps vocab[i] → num_oov + i IN DECLARATION ORDER (matching the string
    StringLookup twin — a vocab declared hot-ids-first keeps that layout in
    the embedding table); everything else hashes into [0, num_oov). The
    search runs over a sorted copy with a position→declaration-index
    permutation applied after.
    """
    v = np.asarray(vocab, np.int32)
    order = np.argsort(v, kind="stable")
    sorted_vocab = jnp.asarray(v[order])
    decl_idx = jnp.asarray(order.astype(np.int32))
    x = jnp.asarray(values, jnp.int32)
    pos = jnp.searchsorted(sorted_vocab, x)
    pos_c = jnp.clip(pos, 0, len(v) - 1)
    found = sorted_vocab[pos_c] == x
    oov = (
        hash_bucket(x.astype(jnp.int32), num_oov)
        if num_oov > 0
        else jnp.zeros_like(pos_c, jnp.int32)
    )
    return jnp.where(found, decl_idx[pos_c] + num_oov, oov)


# ---------------------------------------------------------------------- #
# Host-side (numpy, runs in the data pipeline)


def hash_strings(values, num_bins: int) -> np.ndarray:
    """Deterministic string→bucket hashing (crc32; stable across processes,
    unlike Python's salted hash())."""
    flat = np.asarray(values).reshape(-1)
    out = np.empty(flat.shape[0], np.int32)
    for i, s in enumerate(flat):
        if isinstance(s, bytes):
            b = s
        else:
            b = str(s).encode("utf-8")
        out[i] = zlib.crc32(b) % num_bins
    return out.reshape(np.asarray(values).shape)


class StringLookup:
    """Host-side IndexLookup for string vocabularies.

    vocab[i] → num_oov + i; unknown strings hash into [0, num_oov).
    """

    def __init__(self, vocab: Sequence[str], num_oov: int = 1):
        self.num_oov = num_oov
        self.table: Dict[str, int] = {
            (v if isinstance(v, str) else v.decode("utf-8")): i + num_oov
            for i, v in enumerate(vocab)
        }
        self.vocab_size = len(self.table) + num_oov

    def __call__(self, values) -> np.ndarray:
        flat = np.asarray(values).reshape(-1)
        out = np.empty(flat.shape[0], np.int32)
        for i, s in enumerate(flat):
            key = s.decode("utf-8") if isinstance(s, bytes) else str(s)
            hit = self.table.get(key)
            if hit is None:
                hit = (
                    zlib.crc32(key.encode("utf-8")) % self.num_oov
                    if self.num_oov > 0
                    else 0
                )
            out[i] = hit
        return out.reshape(np.asarray(values).shape)


def pad_to_dense(
    rows: List[Sequence[int]], max_len: int, pad_value: int = -1
) -> np.ndarray:
    """Ragged id lists → (N, max_len) padded-dense int32 with sentinel pads.

    Reference parity: ToSparse/SparseTensor bag inputs. XLA needs static
    shapes, so ragged bags become fixed-width rows; negative ids are treated
    as padding by Embedding/combine.
    """
    out = np.full((len(rows), max_len), pad_value, np.int32)
    for i, r in enumerate(rows):
        r = list(r)[:max_len]
        out[i, : len(r)] = r
    return out


def fit_discretization(values, num_bins: int) -> np.ndarray:
    """Quantile boundaries for `bucketize`, fitted from data — the adapt()
    half of the reference's Discretization layer. Returns num_bins - 1
    boundaries splitting `values` into near-equal-mass buckets; feed them
    to `bucketize` / `feature_spec.bucketized` as plain data.

    Host-side by design: fitting is a one-time ingest-stage pass (like the
    reference's layer adapt before training), not per-step work.
    """
    flat = np.asarray(values, np.float64).reshape(-1)
    flat = flat[np.isfinite(flat)]
    if flat.size == 0 or num_bins < 2:
        return np.zeros((0,), np.float32)
    qs = np.linspace(0.0, 1.0, num_bins + 1)[1:-1]
    # dedupe AFTER the float32 cast: quantiles distinct in float64 can
    # collapse at float32 and duplicated boundaries mean permanently
    # empty buckets (code-review r5 pt7)
    return np.unique(np.quantile(flat, qs).astype(np.float32))


def vocab_from_file(path: str, *, max_size: Optional[int] = None) -> List[str]:
    """One-token-per-line vocabulary file → ordered token list, for
    StringLookup / feature_spec.lookup (reference parity: IndexLookup's
    vocabulary-file constructor; the census zoo shipped its vocabularies
    this way). Blank lines are skipped; duplicates keep first occurrence.
    """
    seen: Dict[str, None] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            tok = line.rstrip("\n")
            if not tok or tok in seen:
                continue
            seen[tok] = None
            if max_size is not None and len(seen) >= max_size:
                break
    return list(seen)
