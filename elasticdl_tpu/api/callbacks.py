"""Master-side training callbacks: the model zoo's `callbacks()` contract.

Reference parity: the reference's zoo modules could export `callbacks()`
(Keras callbacks run around training) and its evaluation service had
early-stop hooks (SURVEY §2.1 evaluation service, §2.5 model zoo contract).
Rebuilt master-side: callbacks observe job-level events — completed eval
jobs, epoch ends, job end — and act through a `JobContext` capability object
(stop training, request a checkpoint). They run in the MASTER process, which
is the only place job-global signals exist (workers only see their own
tasks); this also means they need no model state and survive worker churn.

Contract: `callbacks()` in the zoo module returns a list of objects with any
subset of `on_eval_result(model_version, results)`, `on_epoch_end(epoch)`,
`on_job_end()`. Subclassing `Callback` is optional — the master wires by
duck-typing — but gives `self.ctx` for free.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


class JobContext:
    """What a callback may do to the running job (capability object handed
    to callbacks by the master; see master/main.py wiring)."""

    def __init__(self, dispatcher, servicer=None, evaluation=None):
        self._dispatcher = dispatcher
        self._servicer = servicer
        self._evaluation = evaluation

    def stop_training(self, reason: str = "") -> None:
        """Stop leasing new training tasks; in-flight tasks drain, then the
        job moves to its normal end (final eval/SAVE_MODEL still run)."""
        logger.info("callback requested training stop: %s", reason or "(no reason)")
        self._dispatcher.request_stop_training(reason)

    def request_checkpoint(self, worker_id: int = 0) -> None:
        """Ask a worker (default: the checkpoint-writing worker 0) to save at
        its next task boundary, via the heartbeat should_checkpoint bit."""
        if self._servicer is not None:
            self._servicer.request_checkpoint(worker_id)

    def latest_eval_results(self) -> Dict[str, float]:
        if self._evaluation is None:
            return {}
        return self._evaluation.latest_results()

    def set_learning_rate(self, lr: float) -> None:
        """Push a job-wide LR override to every worker via the heartbeat
        stream; workers apply it at their next task boundary (needs the zoo
        optimizer built through lr_modulation.modulated). Overrides any
        worker-local elastic LR scaling."""
        logger.info("callback set learning rate to %g", lr)
        if self._servicer is not None:
            self._servicer.set_learning_rate(lr)


class Callback:
    """Optional base class; the master calls set_context before any hook."""

    ctx: Optional[JobContext] = None

    def set_context(self, ctx: JobContext) -> None:
        self.ctx = ctx

    def on_eval_result(self, model_version: int, results: Dict[str, float]) -> None:
        pass

    def on_epoch_end(self, epoch: int) -> None:
        pass

    def on_job_end(self) -> None:
        pass


class EarlyStopping(Callback):
    """Stop training when a monitored eval metric stops improving.

    Reference parity: the early-stop hook SURVEY §2.1 lists on the evaluation
    service. `patience` counts completed eval jobs without an improvement of
    at least `min_delta`; on expiry the callback stops task leasing through
    JobContext (and optionally requests a final checkpoint first).
    """

    def __init__(
        self,
        monitor: str = "loss",
        mode: str = "auto",
        patience: int = 3,
        min_delta: float = 0.0,
        checkpoint_on_stop: bool = True,
    ):
        if mode not in ("auto", "min", "max"):
            raise ValueError(f"mode must be auto|min|max, got {mode!r}")
        self.monitor = monitor
        if mode == "auto":
            # losses/errors shrink; everything else (auc, accuracy, …) grows
            mode = "min" if ("loss" in monitor or "error" in monitor) else "max"
        self.mode = mode
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.checkpoint_on_stop = checkpoint_on_stop
        self.best: float = math.inf if mode == "min" else -math.inf
        self.wait = 0
        self.stopped = False

    def _improved(self, value: float) -> bool:
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_eval_result(self, model_version: int, results: Dict[str, float]) -> None:
        if self.stopped:
            return
        value = results.get(self.monitor)
        if value is None:
            logger.warning(
                "EarlyStopping monitors %r but eval results have %s",
                self.monitor, sorted(results),
            )
            return
        if self._improved(value):
            self.best = value
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            self.stopped = True
            reason = (
                f"{self.monitor} did not improve past {self.best:.6g} for "
                f"{self.wait} eval jobs (last {value:.6g} at v{model_version})"
            )
            if self.ctx is not None:
                if self.checkpoint_on_stop:
                    self.ctx.request_checkpoint()
                self.ctx.stop_training(reason)
            else:
                logger.warning("EarlyStopping fired without context: %s", reason)


class ReduceLROnPlateau(Callback):
    """Halve (by `factor`) the job-wide learning rate when a monitored eval
    metric plateaus — the Keras callback the reference's zoo modules could
    return, rebuilt on the master's eval stream + heartbeat LR push.

    Requires the zoo optimizer to be built via `lr_modulation.modulated`
    (injected hyperparams), like elastic LR scaling does; `initial_lr` seeds
    the schedule since the master never sees the optimizer state.
    """

    def __init__(
        self,
        initial_lr: float,
        monitor: str = "loss",
        mode: str = "auto",
        factor: float = 0.5,
        patience: int = 2,
        min_delta: float = 0.0,
        min_lr: float = 0.0,
    ):
        if not (0.0 < factor < 1.0):
            raise ValueError(f"factor must be in (0, 1), got {factor}")
        if mode not in ("auto", "min", "max"):
            raise ValueError(f"mode must be auto|min|max, got {mode!r}")
        if mode == "auto":
            mode = "min" if ("loss" in monitor or "error" in monitor) else "max"
        self.monitor = monitor
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.min_lr = min_lr
        self.lr = float(initial_lr)
        self.best: float = math.inf if mode == "min" else -math.inf
        self.wait = 0

    def _improved(self, value: float) -> bool:
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_eval_result(self, model_version: int, results: Dict[str, float]) -> None:
        value = results.get(self.monitor)
        if value is None:
            logger.warning(
                "ReduceLROnPlateau monitors %r but eval results have %s",
                self.monitor, sorted(results),
            )
            return
        if self._improved(value):
            self.best = value
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience and self.lr > self.min_lr:
            self.lr = max(self.min_lr, self.lr * self.factor)
            self.wait = 0
            logger.info(
                "ReduceLROnPlateau: %s plateaued at %.6g (best %.6g); "
                "lr -> %g", self.monitor, value, self.best, self.lr,
            )
            if self.ctx is not None:
                self.ctx.set_learning_rate(self.lr)
