"""Declarative feature-spec pipeline — tabular models declare features as
data, not code.

Reference parity: elasticdl_preprocessing/layers/*.py (SURVEY §2.5) composed
Hashing / IndexLookup / Discretization / Normalizer / ConcatenateWithOffset
into per-model Keras preprocessing stacks (~1,500 LoC of layer machinery);
census/deepfm declared their features and the stack ran in the TF graph.

TPU-first redesign: a `FeatureSpec` is a list of feature declarations that
COMPILES into two halves instead of a layer graph:

- **host half** (`host_transform`, numpy): everything XLA cannot express —
  string hashing (crc32) and string-vocabulary lookup. Runs once in the data
  pipeline. Features whose source is already numeric pass through untouched.
- **device half** (`device_transform`, jnp): integer hashing, bucketization,
  normalization, integer lookup, and the shared-id-space offset concat. Pure
  jit-friendly ops, applied INSIDE the jitted step so they fuse into the
  model's first matmul instead of burning host CPU (the actual pipeline
  bottleneck — BASELINE.md round-2: the per-record Python loop capped the
  chip 26x).

`transform` is the numpy composition of both halves for per-record parsers
(census CSV) and host-only pipelines; both halves agree bit-for-bit on the
integer id spaces (tests pin host==device).

Ragged multi-valued columns are declared as BAG features (`hashed_bag` /
`lookup_bag`): the host half resolves each ragged row to a fixed-width
(B, max_len) int32 bag with -1 pads (`pad_to_dense`), which Embedding's
combiner consumes directly — the ToSparse/ToRagged path. Bags keep their
own id space (own table per bag) rather than joining the shared offsets.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import numpy as np

from elasticdl_tpu.api import preprocessing as pp

# A feature reads from a named 1-D column ("age") or a column of a packed
# 2-D array (("cat", 3) = cols["cat"][:, 3]) — the latter is how wide
# fixed-layout datasets like Criteo arrive from the batch parsers.
Source = Union[str, Tuple[str, int]]


def _col(cols: Dict[str, Any], source: Source):
    if isinstance(source, tuple):
        key, idx = source
        return cols[key][:, idx]
    return cols[source]


@dataclass(frozen=True)
class Numeric:
    """Dense float feature. transform: None | 'log1p' | ('standard', mean,
    std). Reference parity: Normalizer / the log-squash Criteo transform."""

    name: str
    transform: Any = None
    source: Optional[Source] = None

    @property
    def src(self) -> Source:
        return self.name if self.source is None else self.source

    def apply(self, x, xp):
        v = xp.asarray(x, xp.float32)
        if self.transform is None:
            return v
        if self.transform == "log1p":
            return xp.log1p(xp.maximum(v, 0.0))
        kind, mean, std = self.transform
        if kind != "standard":
            raise ValueError(f"unknown numeric transform {self.transform!r}")
        return (v - xp.float32(mean)) / xp.float32(max(std, 1e-12))


@dataclass(frozen=True)
class Bucketized:
    """Continuous → bucket id in [0, len(boundaries)]. Reference parity:
    Discretization."""

    name: str
    boundaries: Tuple[float, ...]
    source: Optional[Source] = None

    size = property(lambda self: len(self.boundaries) + 1)
    src = property(lambda self: self.name if self.source is None else self.source)

    def apply(self, x, xp):
        b = xp.asarray(np.asarray(self.boundaries, np.float32))
        return xp.searchsorted(
            b, xp.asarray(x, xp.float32), side="right").astype(xp.int32)


@dataclass(frozen=True)
class Hashed:
    """Value → [0, num_bins) by deterministic hash. Reference parity:
    Hashing (the hash trick that bounds the embedding table's key space).
    strings=True sources hash on the HOST (crc32 — XLA has no strings);
    integer sources hash on the DEVICE (Fibonacci multiplicative)."""

    name: str
    num_bins: int
    strings: bool = False
    source: Optional[Source] = None

    size = property(lambda self: self.num_bins)
    src = property(lambda self: self.name if self.source is None else self.source)


@dataclass(frozen=True)
class Lookup:
    """Static-vocabulary lookup: vocab[i] → num_oov + i, unknown → hash
    into [0, num_oov). Reference parity: IndexLookup. A string vocab runs
    on the host, an integer vocab on the device."""

    name: str
    vocab: Tuple[Any, ...]
    num_oov: int = 1
    source: Optional[Source] = None

    size = property(lambda self: len(self.vocab) + self.num_oov)
    src = property(lambda self: self.name if self.source is None else self.source)

    @property
    def strings(self) -> bool:
        return bool(self.vocab) and isinstance(self.vocab[0], (str, bytes))


@dataclass(frozen=True)
class HashedBag:
    """Multi-valued (ragged) categorical → fixed-width padded id bag.
    Reference parity: ToSparse/ToRagged + Hashing feeding an embedding
    with a combiner. XLA needs static shapes, so the ragged bag becomes a
    (B, max_len) int32 row with -1 pads — exactly what Embedding's
    combiner treats as padding. Bags keep their OWN id space [0, num_bins)
    (own embedding table per bag), so they don't join the shared offset
    space. Resolution is inherently host-side (ragged → static)."""

    name: str
    num_bins: int
    max_len: int
    strings: bool = False
    delimiter: str = "|"
    source: Optional[Source] = None

    size = property(lambda self: self.num_bins)
    src = property(lambda self: self.name if self.source is None else self.source)

    def elem_ids(self, elems) -> np.ndarray:
        if not len(elems):
            return np.empty((0,), np.int32)
        if self.strings:
            return pp.hash_strings(list(elems), self.num_bins)
        return _np_hash_bucket(
            np.asarray(list(elems)).astype(np.int32), self.num_bins)


@dataclass(frozen=True)
class LookupBag:
    """HashedBag's vocabulary twin: elements map vocab[i] → num_oov + i in
    declaration order, unknowns hash into [0, num_oov)."""

    name: str
    vocab: Tuple[Any, ...]
    max_len: int
    num_oov: int = 1
    delimiter: str = "|"
    source: Optional[Source] = None

    size = property(lambda self: len(self.vocab) + self.num_oov)
    src = property(lambda self: self.name if self.source is None else self.source)

    @property
    def strings(self) -> bool:
        return bool(self.vocab) and isinstance(self.vocab[0], (str, bytes))

    def _table(self) -> "pp.StringLookup":
        """Per-instance cached StringLookup (frozen dataclass, so cache
        through object.__setattr__) — building the |vocab| dict once, not
        per row."""
        t = getattr(self, "_cached_table", None)
        if t is None:
            t = pp.StringLookup(
                [v if isinstance(v, str) else v.decode("utf-8")
                 for v in self.vocab], self.num_oov)
            object.__setattr__(self, "_cached_table", t)
        return t

    def elem_ids(self, elems) -> np.ndarray:
        if not len(elems):
            return np.empty((0,), np.int32)
        if self.strings:
            return self._table()(list(elems))
        return _np_int_lookup(
            np.asarray(list(elems)).astype(np.int32), self.vocab, self.num_oov)


BagFeature = Union[HashedBag, LookupBag]
FeatureDef = Union[Numeric, Bucketized, Hashed, Lookup, HashedBag, LookupBag]


def numeric(name: str, *, standardize: Optional[Tuple[float, float]] = None,
            log1p: bool = False, source: Optional[Source] = None) -> Numeric:
    if standardize is not None and log1p:
        raise ValueError("choose standardize OR log1p, not both")
    t = ("standard", *standardize) if standardize is not None else (
        "log1p" if log1p else None)
    return Numeric(name, t, source)


def bucketized(name: str, boundaries: Sequence[float], *,
               source: Optional[Source] = None) -> Bucketized:
    return Bucketized(name, tuple(float(b) for b in boundaries), source)


def hashed(name: str, num_bins: int, *, strings: bool = False,
           source: Optional[Source] = None) -> Hashed:
    return Hashed(name, int(num_bins), strings, source)


def lookup(name: str, vocab: Sequence[Any], *, num_oov: int = 1,
           source: Optional[Source] = None) -> Lookup:
    return Lookup(name, tuple(vocab), int(num_oov), source)


def hashed_bag(name: str, num_bins: int, max_len: int, *,
               strings: bool = False, delimiter: str = "|",
               source: Optional[Source] = None) -> HashedBag:
    return HashedBag(name, int(num_bins), int(max_len), strings, delimiter,
                     source)


def lookup_bag(name: str, vocab: Sequence[Any], max_len: int, *,
               num_oov: int = 1, delimiter: str = "|",
               source: Optional[Source] = None) -> LookupBag:
    return LookupBag(name, tuple(vocab), int(max_len), int(num_oov),
                     delimiter, source)


def _np_hash_bucket(ids, num_bins: int) -> np.ndarray:
    """Numpy twin of pp.hash_bucket (bit-identical; tests pin it)."""
    x = np.asarray(ids).astype(np.uint32)
    x = x ^ (x >> np.uint32(16))
    x = (x * np.uint32(0x85EBCA6B)).astype(np.uint32)
    x = x ^ (x >> np.uint32(13))
    x = (x * np.uint32(0xC2B2AE35)).astype(np.uint32)
    x = x ^ (x >> np.uint32(16))
    return (x % np.uint32(num_bins)).astype(np.int32)


def _np_int_lookup(values, vocab, num_oov: int) -> np.ndarray:
    """Numpy twin of pp.int_lookup: declaration-order ids (vocab[i] →
    num_oov + i), sorted search + permutation."""
    v = np.asarray(vocab, np.int32)
    order = np.argsort(v, kind="stable")
    sv, decl_idx = v[order], order.astype(np.int32)
    x = np.asarray(values, np.int32)
    pos = np.searchsorted(sv, x)
    pos_c = np.clip(pos, 0, len(v) - 1)
    found = sv[pos_c] == x
    oov = (_np_hash_bucket(x, num_oov) if num_oov > 0
           else np.zeros_like(pos_c, np.int32))
    return np.where(found, decl_idx[pos_c] + num_oov, oov)


class FeatureSpec:
    """An ordered feature list compiled into (host, device) transforms.

    Output contract (the shape every tabular zoo model consumes):
      {"dense": (B, dense_dim) float32,
       "cat":   (B, cat_dim)   int32 in ONE shared id space of
                `total_vocab` rows (per-feature offsets applied),
       "bags":  {name: (B, max_len) int32, pad=-1} — only when bag
                features are declared; each bag keeps its own id space of
                `feature.size` rows (own embedding + combiner)}
    """

    def __init__(self, features: Sequence[FeatureDef]):
        if not features:
            raise ValueError("FeatureSpec needs at least one feature")
        names = [f.name for f in features]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate feature names in {names}")
        self.features = tuple(features)
        self.dense_features = tuple(
            f for f in features if isinstance(f, Numeric))
        self.bag_features = tuple(
            f for f in features if isinstance(f, (HashedBag, LookupBag)))
        self.cat_features = tuple(
            f for f in features
            if not isinstance(f, (Numeric, HashedBag, LookupBag)))
        self.dense_dim = len(self.dense_features)
        self.cat_dim = len(self.cat_features)
        self.offsets: Dict[str, int] = {}
        off = 0
        for f in self.cat_features:
            self.offsets[f.name] = off
            off += f.size
        self.total_vocab = off
        self._host_lookups = {
            f.name: pp.StringLookup(
                [v if isinstance(v, str) else v.decode("utf-8")
                 for v in f.vocab], f.num_oov)
            for f in self.cat_features
            if isinstance(f, Lookup) and f.strings
        }

    @staticmethod
    def _resolve_bag(f: BagFeature, x) -> np.ndarray:
        """Ragged column → (B, max_len) padded ids. Accepts rows that are
        sequences (lists/arrays), delimiter-joined strings, or bare
        scalars (single-element bag); None/NaN/empty → all-pad row.
        (LookupBag caches its own StringLookup per instance.)"""
        rows = []
        for r in np.asarray(x, dtype=object).reshape(-1):
            if r is None or (isinstance(r, (float, np.floating))
                             and np.isnan(r)):
                elems = []
            elif isinstance(r, (str, bytes)):
                s = r.decode("utf-8") if isinstance(r, bytes) else r
                elems = [e.strip() for e in s.split(f.delimiter) if e.strip()]
            elif np.isscalar(r):
                elems = [r]
            else:
                elems = list(r)
            rows.append(f.elem_ids(elems))
        return pp.pad_to_dense(rows, f.max_len)

    # ------------------------------------------------------------------ #
    # host half

    def host_transform(self, cols: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Resolve everything XLA can't: string hash / string lookup become
        final ids in [0, size); every other feature passes through raw
        under its feature name. Output feeds device_transform."""
        out: Dict[str, np.ndarray] = {}
        for f in self.features:
            x = _col(cols, f.src)
            if isinstance(f, (HashedBag, LookupBag)):
                out[f.name] = self._resolve_bag(f, x)   # ragged → static
            elif isinstance(f, Hashed) and f.strings:
                out[f.name] = pp.hash_strings(x, f.num_bins)
            elif isinstance(f, Lookup) and f.strings:
                out[f.name] = self._host_lookups[f.name](x)
            elif isinstance(f, Numeric) or isinstance(f, Bucketized):
                out[f.name] = np.asarray(x, np.float32)
            else:
                out[f.name] = np.asarray(x, np.int32)
        return out

    # ------------------------------------------------------------------ #
    # device half (jnp — call inside the jitted step / model)

    def device_transform(self, inter: Dict[str, Any]) -> Dict[str, Any]:
        """Host-resolved intermediate → {"dense", "cat"}; pure jnp ops that
        fuse into the step. String-sourced features arrive as final ids and
        only get their offset.

        `inter` is keyed by feature name (host_transform output) OR, for an
        all-numeric spec, by raw source columns — so a model whose inputs
        are packed arrays (Criteo "dense"/"cat") can apply the WHOLE spec
        inside its jitted __call__ with no host half at all."""
        import jax.numpy as jnp

        def col(f):
            return inter[f.name] if f.name in inter else _col(inter, f.src)

        dense = [f.apply(col(f), jnp) for f in self.dense_features]
        cat = []
        for f in self.cat_features:
            if (isinstance(f, Hashed) and f.strings) or (
                    isinstance(f, Lookup) and f.strings):
                if f.name not in inter:
                    raise ValueError(
                        f"string feature {f.name!r} needs host_transform "
                        "before device_transform")
            x = col(f)
            if isinstance(f, Bucketized):
                ids = f.apply(x, jnp)
            elif isinstance(f, Hashed) and not f.strings:
                ids = pp.hash_bucket(x, f.num_bins)
            elif isinstance(f, Lookup) and not f.strings:
                ids = pp.int_lookup(x, f.vocab, f.num_oov)
            else:   # host-resolved string feature: already final ids
                ids = jnp.asarray(x, jnp.int32)
            cat.append(ids + jnp.int32(self.offsets[f.name]))
        out = {}
        if dense:
            out["dense"] = jnp.stack(dense, axis=-1)
        if cat:
            out["cat"] = jnp.stack(cat, axis=-1)
        if self.bag_features:
            # bags are host-resolved (ragged → static is host work); the
            # device half only casts — keeping one output contract
            for f in self.bag_features:
                if f.name not in inter:
                    raise ValueError(
                        f"bag feature {f.name!r} needs host_transform "
                        "before device_transform")
            out["bags"] = {
                f.name: jnp.asarray(inter[f.name], jnp.int32)
                for f in self.bag_features
            }
        return out

    # ------------------------------------------------------------------ #
    # numpy composition (per-record parsers, host-only pipelines, tests)

    def transform(self, cols: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """host_transform ∘ device-half-in-numpy. Bit-identical id spaces
        with the device half (pinned by tests/test_feature_spec.py)."""
        inter = self.host_transform(cols)
        dense = [f.apply(inter[f.name], np) for f in self.dense_features]
        cat = []
        for f in self.cat_features:
            x = inter[f.name]
            if isinstance(f, Bucketized):
                ids = f.apply(x, np)
            elif isinstance(f, Hashed) and not f.strings:
                ids = _np_hash_bucket(x, f.num_bins)
            elif isinstance(f, Lookup) and not f.strings:
                ids = _np_int_lookup(x, f.vocab, f.num_oov)
            else:
                ids = np.asarray(x, np.int32)
            cat.append(ids + np.int32(self.offsets[f.name]))
        out: Dict[str, np.ndarray] = {}
        if dense:
            out["dense"] = np.stack(dense, axis=-1).astype(np.float32)
        if cat:
            out["cat"] = np.stack(cat, axis=-1).astype(np.int32)
        if self.bag_features:
            out["bags"] = {f.name: inter[f.name] for f in self.bag_features}
        return out

    def transform_row(self, row: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """One record (dict of scalars; bag cells may be lists or
        delimiter-joined strings; packed sources take the full row
        sequence) → {"dense": (n,), "cat": (m,)} (+ "bags":
        {name: (max_len,)} when declared)."""
        bag_srcs = {f.src for f in self.bag_features
                    if isinstance(f.src, str)}

        def one(k, v):
            if k in bag_srcs:
                # a single-slot object array holds a list/str bag cell
                # intact (np.asarray([list]) would promote it to a 2-D
                # row and _resolve_bag would see elements as rows)
                a = np.empty((1,), dtype=object)
                a[0] = v
                return a
            # non-bag cells: plain batch-of-one — a sequence cell becomes
            # the (1, width) row that packed ("key", j) sources index
            return np.asarray([v])

        out = self.transform({k: one(k, v) for k, v in row.items()})
        return {
            k: ({n: b[0] for n, b in v.items()} if k == "bags" else v[0])
            for k, v in out.items()
        }

    # ------------------------------------------------------------------ #
    # CSV convenience: spec + column order -> reader parse function

    def csv_parser(
        self,
        columns: Sequence[str],
        label_fn: Callable[[Dict[str, str]], Any],
        delimiter: str = ",",
    ):
        """parse(record: bytes) -> (features, label) for CSV readers; the
        per-row twin of the reference's feature-column input_fn."""
        columns = tuple(columns)

        def parse(record: bytes):
            parts = [p.strip()
                     for p in record.decode("utf-8").rstrip("\n").split(delimiter)]
            row = dict(zip(columns, parts))
            typed: Dict[str, Any] = {}
            for f in self.features:
                src = f.src
                if isinstance(src, tuple):
                    raise ValueError(
                        "csv_parser needs named-column sources; "
                        f"{f.name} reads {src}")
                raw = row.get(src, "")
                needs_string = (
                    isinstance(f, (HashedBag, LookupBag))  # split later by
                    # the bag's own delimiter in _resolve_bag
                    or (isinstance(f, Hashed) and f.strings)
                    or (isinstance(f, Lookup) and f.strings)
                )
                typed[src] = raw if needs_string else float(raw or 0)
            return self.transform_row(typed), label_fn(row)

        return parse
