"""EDL3xx: RPC / control-plane hygiene.

PR 1 hardened the wire layer (RetryingMasterStub: deadlines, idempotent-
only retries, circuit breaker). These rules keep callers from quietly
eroding that hardening:

EDL301 bare-master-stub
    `MasterStub(...)` constructed outside proto/service.py: every
    production caller must go through RetryingMasterStub, or it loses
    deadlines, the breaker, and the fault-injection sites.

EDL302 rpc-missing-deadline
    a known Master-RPC method invoked without `timeout=`, when the
    receiver was locally bound to a bare `MasterStub(...)` (tracked by
    assignment within the module). RetryingMasterStub carries per-RPC
    policy deadlines, so its callers may omit timeout; a bare stub call
    without one blocks forever on a half-dead master.

EDL303 silent-exception-swallow
    a broad handler (bare `except`, `Exception`, `BaseException`) whose
    body neither logs nor raises nor does anything else (only
    pass/.../continue/return-constant). A narrowed handler
    (`except OSError: pass`) is a reviewed decision and is not flagged.

EDL304 sleep-retry-no-jitter
    constant-argument `time.sleep` inside a loop that also catches
    exceptions (the retry shape). Synchronized constant backoff is how a
    relaunched fleet produces a thundering herd against a recovering
    master; use the stub's jittered backoff or randomize the sleep.

EDL305 non-atomic-state-file-write
    `open(..., "w")` onto a `*.json`/`*.jsonl` state file in a scope that
    never calls `os.replace`/`os.rename`. A crash mid-write leaves a torn
    file the next reader chokes on; the required idiom is write-to-a-
    `.tmp`-sibling + fsync + `os.replace` (the journal and
    membership_signal writers are the reference implementations —
    master/journal.py `_rotate_locked`, common/membership_signal.py
    `write_signal`). Opening the `.tmp` sibling itself, append-mode
    handles (a WAL's appends are torn-tail-tolerant by design), and
    scopes that do replace/rename are all quiet.

EDL208 rpc-call-without-deadline
    an embedding DATA-PLANE stub call (the EmbeddingPull/Push/
    FetchShard/FetchDelta/Watermark RPC surface, or any method on a
    local bound to a bare `DataPlaneStub(...)`) without a `timeout=`
    argument. The data plane is the partition-critical path (ISSUE 15):
    a deadline-less call against a blackholed owner blocks its worker
    thread for the channel's whole connect saga, exactly the failure
    the deadline-budget machinery exists to bound. The reference
    fixture is embedding/data_plane.py's GrpcTransport, which threads
    every call's budget down as the gRPC deadline; production callers
    go through it (or ResilientTransport), never a bare stub. Numbered
    in the EDL2xx embedding family (EDL206/EDL207's sibling) despite
    living here with its RPC-hygiene kin. Lint targets are the package
    tree — tests (outside it) may hold deadline-less calls to probe
    the failure mode itself.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from elasticdl_tpu.analysis.core import Finding, ModuleContext, Rule, register

#: the Master service RPC surface (proto/service.py _RPCS)
RPC_METHODS = {
    "RegisterWorker", "GetTask", "ReportTaskResult",
    "ReportEvaluationMetrics", "Heartbeat", "GetJobStatus",
}

#: modules allowed to construct the bare stub (the wrapper itself)
_BARE_STUB_ALLOWED = ("proto/service.py",)

_LOG_NAMES = {"logger", "logging", "log", "warnings", "print"}


def _is_call_to(node: ast.AST, name: str) -> bool:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id == name:
            return True
        if isinstance(f, ast.Attribute) and f.attr == name:
            return True
    return False


@register
class BareMasterStubRule(Rule):
    id = "EDL301"
    name = "bare-master-stub"
    doc = (
        "MasterStub constructed outside proto/service.py — use "
        "RetryingMasterStub (deadlines, retries, breaker, fault sites)"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.rel_path.endswith(_BARE_STUB_ALLOWED):
            return
        for node in ast.walk(ctx.tree):
            if _is_call_to(node, "MasterStub"):
                yield self.finding(
                    ctx, node,
                    "bare MasterStub bypasses RetryingMasterStub "
                    "(no deadline policy, no retries, no circuit breaker)",
                )


@register
class RpcMissingDeadlineRule(Rule):
    id = "EDL302"
    name = "rpc-missing-deadline"
    doc = (
        "Master RPC on a bare MasterStub without timeout= — blocks "
        "forever against a half-dead master"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        bare = self._bare_stub_names(ctx)
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in RPC_METHODS
            ):
                continue
            recv = node.func.value
            is_bare = (
                isinstance(recv, ast.Name) and recv.id in bare
            ) or _is_call_to(recv, "MasterStub")
            if not is_bare:
                continue
            if not any(kw.arg == "timeout" for kw in node.keywords):
                yield self.finding(
                    ctx, node,
                    f"{node.func.attr} on a bare MasterStub without "
                    "timeout= has no deadline at all",
                )

    def _bare_stub_names(self, ctx: ModuleContext) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and _is_call_to(
                node.value, "MasterStub"
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names


#: the embedding data-plane RPC surface (embedding/data_plane.py
#: _DATA_RPCS) — names unique enough that ANY call spelling is a stub
#: call (the servicer's same-named methods are definitions, not calls)
DATA_PLANE_RPCS = {
    "EmbeddingPull", "EmbeddingPush", "EmbeddingFetchShard",
    "EmbeddingFetchDelta", "EmbeddingWatermark",
    # wire-speed lane (ISSUE 18): fused pulls, shm negotiation, and the
    # streaming fetch variants are data-plane calls like any other —
    # a deadline-less call still wedges on a partitioned owner
    "EmbeddingPullMulti", "EmbeddingWatermarkMulti",
    "EmbeddingShmNegotiate", "EmbeddingFetchShardStream",
    "EmbeddingFetchDeltaStream",
}


@register
class DataPlaneCallWithoutDeadlineRule(Rule):
    id = "EDL208"
    name = "rpc-call-without-deadline"
    doc = (
        "embedding data-plane stub call without timeout= — blocks a "
        "worker thread for the whole connect saga against a "
        "partitioned owner; route through GrpcTransport/"
        "ResilientTransport (deadline budgets) or pass timeout="
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        bare = self._bare_stub_names(ctx)
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            method = node.func.attr
            recv = node.func.value
            is_data_call = method in DATA_PLANE_RPCS or (
                isinstance(recv, ast.Name) and recv.id in bare
            ) or _is_call_to(recv, "DataPlaneStub")
            if not is_data_call:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            yield self.finding(
                ctx, node,
                f"data-plane call {method} without timeout= has no "
                "deadline — it will block for the channel's whole "
                "connect saga against a partitioned owner",
            )

    def _bare_stub_names(self, ctx: ModuleContext) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and _is_call_to(
                node.value, "DataPlaneStub"
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names


def _body_is_silent(body: List[ast.stmt]) -> bool:
    """True when the handler body visibly does nothing with the error."""
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        if isinstance(stmt, ast.Return):
            v = stmt.value
            if v is None or isinstance(v, ast.Constant):
                continue
            return False
        return False
    return True


def _is_broad_exception(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in [t] + (list(t.elts) if isinstance(t, ast.Tuple) else []):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in ("Exception", "BaseException") for n in names)


@register
class SilentExceptionSwallowRule(Rule):
    id = "EDL303"
    name = "silent-exception-swallow"
    doc = (
        "broad except whose body neither logs nor raises — failures "
        "disappear; narrow the type, log, or re-raise"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_exception(node):
                continue
            if _body_is_silent(node.body):
                yield self.finding(
                    ctx, node,
                    "broad except silently swallows the error; narrow the "
                    "exception type, log it, or re-raise",
                )


def _module_str_constants(tree: ast.Module) -> dict:
    """Top-level `NAME = "literal"` assignments (state-file names are
    conventionally module constants, e.g. export.py's INFO_FILE)."""
    out = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _open_write_mode(call: ast.Call) -> bool:
    """True for open(...) with an explicit write/truncate mode. Append
    ("a") is deliberately quiet: an append-only log's durability story is
    torn-tail tolerance, not whole-file atomicity."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and mode.value.startswith("w")
    )


def _json_state_path(expr: ast.AST, consts: dict) -> bool:
    """True when the path expression names a .json/.jsonl file and is NOT
    the .tmp sibling (writing the tmp file IS the atomic idiom's first
    half)."""
    json_like = tmp_like = False
    for node in ast.walk(expr):
        s = None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            s = node.value
        elif isinstance(node, ast.Name):
            s = consts.get(node.id)
        if s is None:
            continue
        if ".json" in s:
            json_like = True
        if ".tmp" in s:
            tmp_like = True
    return json_like and not tmp_like


@register
class NonAtomicStateFileWriteRule(Rule):
    id = "EDL305"
    name = "non-atomic-state-file-write"
    doc = (
        "open(*.json, 'w') without the tmp-sibling + os.replace idiom — "
        "a crash mid-write leaves a torn state file"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        consts = _module_str_constants(ctx.tree)
        yield from self._scan_scope(ctx, ctx.tree, consts)

    def _scan_scope(
        self, ctx: ModuleContext, scope: ast.AST, consts: dict
    ) -> Iterator[Finding]:
        """One function body (or the module top level): flag candidate
        writes only when the scope never replaces/renames — a scope that
        does is taken to be implementing the atomic idiom."""
        candidates: List[ast.Call] = []
        replaces = False
        inner: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner.append(node)
                continue
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "open"
                    and node.args
                    and _open_write_mode(node)
                    and _json_state_path(node.args[0], consts)
                ):
                    candidates.append(node)
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("replace", "rename")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "os"
                ):
                    replaces = True
            stack.extend(ast.iter_child_nodes(node))
        if not replaces:
            for call in candidates:
                yield self.finding(
                    ctx, call,
                    "non-atomic overwrite of a JSON state file: write a "
                    ".tmp sibling and os.replace() it (crash mid-write "
                    "otherwise leaves a torn file for the next reader)",
                )
        for fn in inner:
            yield from self._scan_scope(ctx, fn, consts)


@register
class SleepRetryNoJitterRule(Rule):
    id = "EDL304"
    name = "sleep-retry-no-jitter"
    doc = (
        "constant time.sleep in a retry loop — synchronized backoff "
        "(thundering herd); add jitter or use the stub's backoff"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            has_try = any(
                isinstance(sub, ast.Try)
                for stmt in loop.body
                for sub in ast.walk(stmt)
            )
            if not has_try:
                continue
            for stmt in loop.body:
                for sub in ast.walk(stmt):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "sleep"
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "time"
                        and sub.args
                        and isinstance(sub.args[0], ast.Constant)
                    ):
                        yield self.finding(
                            ctx, sub,
                            "constant sleep in a retry loop synchronizes "
                            "retries across workers; jitter it (e.g. "
                            "uniform(0.5, 1.5) * base) or reuse the stub's "
                            "backoff",
                        )
