"""EDL1xx lock-discipline family: whole-program concurrency analysis.

Three ProjectRules built on one shared model (`ConcurrencyModel`,
memoized on the ProjectContext):

- EDL102 lock-order-inversion — every `with self.<lock>:` site is an
  acquisition node; held-lock sets are propagated interprocedurally
  (through the call graph, seeded from `with` nesting, `# holds:`
  declarations, and the `_locked` naming idiom), producing a static
  lock-acquisition graph whose cycles are potential deadlocks. The
  runtime recorder (`lockorder.py`) only sees orders that executed;
  this sees every order the code can express. `--lock-graph` emits the
  graph (JSON/DOT) for CI artifacts and the runtime-superset
  cross-check in tests/test_lock_order.py.

- EDL103 blocking-call-under-lock — "may block" (sleep, Commit.wait /
  Event.wait, queue get/put, subprocess, socket/file I/O, os.fsync, RPC
  stubs) is propagated through the call graph; any may-block call made
  while a lock is held is flagged, generalizing the lexical EDL403
  beyond fsync. A reviewed `# edl-lint: disable=EDL103` ON the blocking
  line sanctions the site AND stops propagation through it (the journal
  committer's fsync is the canonical sanctioned site).

- EDL104 guarded-state-escape — a `# guarded_by:` MUTABLE attribute
  whose REFERENCE leaves the critical section: returned/yielded, stored
  onto another object, aliased to a differently-guarded attribute, or
  captured by a thread/queue/executor sink, without a copy taken inside
  the lock. This is the aliasing gap locks.py's EDL101 concedes by
  design — EDL101 proves accesses happen under the lock; EDL104 proves
  the lock still means something after the method returns.

Lock identity is `ClassName.attr` abstracted over instances, with the
master control plane's canonical runtime names (the ones
`lockorder.instrument_master` registers) substituted where known, so
the static graph and the runtime recorder's edges share a vocabulary.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from elasticdl_tpu.analysis.callgraph import CallGraph, ClassInfo, FunctionInfo
from elasticdl_tpu.analysis.core import (
    Finding,
    ModuleContext,
    ProjectContext,
    ProjectRule,
    register,
)
from elasticdl_tpu.analysis.locks import (
    _CONSTRUCTION_METHODS,
    _HOLDS_RE,
    guarded_attrs,
)

#: (class name, lock attr) -> the canonical name lockorder.instrument_master
#: registers that lock under at runtime. Keep the two in sync: the
#: cross-check test asserts the static graph over master/ is a superset
#: of the runtime recorder's edges BY THESE NAMES.
CANONICAL_LOCK_NAMES: Dict[Tuple[str, str], str] = {
    ("Membership", "_lock"): "membership",
    ("TaskDispatcher", "_lock"): "dispatcher",
    ("ProcessManager", "_lock"): "process_manager",
    ("MasterServicer", "_loss_lock"): "servicer.loss",
    ("MasterServicer", "_ctrl_lock"): "servicer.ctrl",
    ("EvaluationService", "_lock"): "evaluation",
    ("ControlPlaneJournal", "_lock"): "journal.file",
    ("ControlPlaneJournal", "_qcv"): "journal.queue",
    ("Autoscaler", "_lock"): "autoscaler",
}

#: attr names treated as locks even without a visible threading.X()
#: construction (helper-assigned locks, fixture classes)
_LOCKISH_NAME_RE = re.compile(r"(^_.*lock\w*$|^_qcv$|^_cv$|^_cond\w*$)")

#: containers whose construction marks a guarded attr as MUTABLE
_MUTABLE_CTORS = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter", "bytearray",
}
_MUTABLE_ANN_RE = re.compile(
    r"\b(Dict|List|Set|MutableMapping|MutableSequence|MutableSet|"
    r"deque|defaultdict|DefaultDict|OrderedDict|dict|list|set)\b"
)

#: calls that take a snapshot: a copy wrapped around the guarded attr
#: inside the lock makes the escape safe
_COPY_CALLS = {
    "dict", "list", "tuple", "set", "frozenset", "sorted", "copy",
    "deepcopy", "replace", "asdict",
}


def lock_node(class_name: str, attr: str) -> str:
    """Graph-node name for a class's lock attribute."""
    return CANONICAL_LOCK_NAMES.get((class_name, attr), f"{class_name}.{attr}")


# ------------------------------------------------------------------ #
# shared model


@dataclass
class _Acquire:
    lock: str                     # node name
    held: Tuple[str, ...]         # nodes held at this acquisition
    node: ast.AST
    module: ModuleContext
    kind: str                     # "lock" | "rlock" | "condition"
    suppressed: bool              # reviewed disable=EDL102 on the site


@dataclass
class _CallSite:
    call: ast.Call
    callees: Tuple[str, ...]      # FunctionInfo keys
    held: Tuple[str, ...]
    node: ast.AST
    module: ModuleContext


@dataclass
class _Blocker:
    desc: str                     # e.g. "time.sleep()"
    held: Tuple[str, ...]
    node: ast.AST
    module: ModuleContext
    sanctioned: bool              # disable=EDL103 on the line: no local
                                  # finding AND no propagation to callers


@dataclass
class _FnSummary:
    info: FunctionInfo
    entry_holds: Tuple[str, ...] = ()
    acquires: List[_Acquire] = field(default_factory=list)
    calls: List[_CallSite] = field(default_factory=list)
    blockers: List[_Blocker] = field(default_factory=list)


class _ModuleAliases:
    """Import-aware names for the blocking primitives one module can
    reach: `time.sleep` aliases, `os.fsync`, subprocess entry points."""

    def __init__(self, ctx: ModuleContext):
        self.time_sleep: Set[str] = set()       # sleep / snooze / ...
        self.time_mods: Set[str] = set()        # time / walltime / ...
        self.os_mods: Set[str] = set()
        self.os_funcs: Set[str] = set()         # fsync/fdatasync from-imports
        self.subprocess_mods: Set[str] = set()
        self.subprocess_funcs: Set[str] = set() # run/check_call/Popen/...
        self.socket_mods: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = (a.asname or a.name).split(".")[0]
                    if a.name == "time":
                        self.time_mods.add(local)
                    elif a.name == "os":
                        self.os_mods.add(local)
                    elif a.name == "subprocess":
                        self.subprocess_mods.add(local)
                    elif a.name == "socket":
                        self.socket_mods.add(local)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    local = a.asname or a.name
                    if node.module == "time" and a.name == "sleep":
                        self.time_sleep.add(local)
                    elif node.module == "os" and a.name in (
                        "fsync", "fdatasync"
                    ):
                        self.os_funcs.add(local)
                    elif node.module == "subprocess" and a.name in (
                        "run", "call", "check_call", "check_output", "Popen"
                    ):
                        self.subprocess_funcs.add(local)


def _dotted_tail(expr: ast.AST) -> str:
    """Terminal identifier of a receiver expression ('' if none)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output", "Popen"}
_SOCKET_METHODS = {"recv", "recv_into", "accept", "connect", "sendall"}
_THREADISH_RE = re.compile(r"(thread|committer|watcher|poller|proc)", re.I)
_QUEUEISH_RE = re.compile(r"(queue|_q\d*$)", re.I)
_STUBISH_RE = re.compile(r"stub", re.I)


def _classify_blocker(
    call: ast.Call, aliases: _ModuleAliases
) -> Optional[str]:
    """Human-readable description if this call can block, else None.
    Condition-wait exemption is applied by the caller (needs held-set)."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in aliases.time_sleep:
            return "time.sleep()"
        if f.id in aliases.os_funcs:
            return f"os.{f.id}() (disk flush)"
        if f.id in aliases.subprocess_funcs:
            return f"subprocess.{f.id}() (process spawn/wait)"
        if f.id == "open":
            return "open() (file I/O)"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    recv, method = f.value, f.attr
    recv_name = _dotted_tail(recv)
    if method == "sleep" and isinstance(recv, ast.Name) \
            and recv.id in aliases.time_mods:
        return "time.sleep()"
    if method in ("fsync", "fdatasync") and isinstance(recv, ast.Name) \
            and recv.id in aliases.os_mods:
        return f"os.{method}() (disk flush)"
    if method in _SUBPROCESS_BLOCKING and isinstance(recv, ast.Name) \
            and recv.id in aliases.subprocess_mods:
        return f"subprocess.{method}() (process spawn/wait)"
    if method == "wait":
        # Commit.wait / Event.wait / Condition.wait / Popen.wait — all
        # block; the Condition-on-the-innermost-held-lock idiom is
        # exempted by the caller, which knows the held set
        return f"{recv_name or '<recv>'}.wait()"
    if method == "communicate":
        return f"{recv_name}.communicate() (subprocess drain)"
    if method == "result" and not isinstance(recv, ast.Call):
        return f"{recv_name}.result() (future wait)"
    if method == "join" and _THREADISH_RE.search(recv_name or ""):
        return f"{recv_name}.join() (thread join)"
    if method in _SOCKET_METHODS and (
        (isinstance(recv, ast.Name) and recv.id in aliases.socket_mods)
        or re.search(r"(sock|conn|chan)", recv_name or "", re.I)
    ):
        return f"{recv_name}.{method}() (socket I/O)"
    if method in ("get", "put") and _QUEUEISH_RE.search(recv_name or ""):
        blocking = True
        args = list(call.args)
        if len(args) >= (2 if method == "put" else 1):
            blk = args[1] if method == "put" else args[0]
            if isinstance(blk, ast.Constant) and blk.value is False:
                blocking = False
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                blocking = False
        if blocking:
            return f"{recv_name}.{method}() (queue wait)"
        return None
    if _STUBISH_RE.search(recv_name or "") and method[:1].isupper():
        return f"{recv_name}.{method}() (RPC)"
    return None


class _FunctionVisitor(ast.NodeVisitor):
    """One pass over a def: tracks the lexically-held lock-node stack,
    recording acquisitions, resolvable calls, and blocking primitives."""

    def __init__(
        self,
        model: "ConcurrencyModel",
        info: FunctionInfo,
        cls: Optional[ClassInfo],
        entry_holds: Tuple[str, ...],
    ):
        self.model = model
        self.info = info
        self.cls = cls
        self.ctx = info.module
        self.aliases = model.aliases(info.module)
        self.locks = model.class_locks(cls) if cls is not None else {}
        self.held: List[str] = list(entry_holds)
        self.summary = _FnSummary(info=info, entry_holds=entry_holds)
        self.local_types = model.graph.local_types(info.node)

    # ---- lock regions ---- #

    def _with_locks(self, node: ast.With) -> List[Tuple[str, str, ast.AST]]:
        """(node-name, kind, item-node) for each lock this with acquires."""
        out = []
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and self.cls is not None
            ):
                attr = expr.attr
                kind = self.locks.get(attr)
                if kind is None and _LOCKISH_NAME_RE.match(attr):
                    kind = "lock"
                if kind is not None:
                    out.append(
                        (lock_node(self.cls.name, attr), kind, expr)
                    )
            elif isinstance(expr, ast.Name):
                kind = self.model.module_lock_kind(self.ctx, expr.id)
                if kind is not None:
                    out.append(
                        (f"{self.ctx.rel_path}:{expr.id}", kind, expr)
                    )
        return out

    def visit_With(self, node: ast.With) -> None:
        acquired = self._with_locks(node)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for name, kind, expr in acquired:
            self.summary.acquires.append(_Acquire(
                lock=name, held=tuple(self.held), node=node,
                module=self.ctx, kind=kind,
                suppressed=self.model.site_disabled(self.ctx, node, "edl102"),
            ))
            self.held.append(name)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    # nested defs / lambdas run later, on whatever thread calls them:
    # their bodies get an empty held-set and do NOT contribute calls or
    # blockers to THIS function's summary (they are summarized — and
    # charged — only if the call graph reaches them by name)

    def _deferred(self, node: ast.AST) -> None:
        return  # do not descend

    visit_FunctionDef = _deferred
    visit_AsyncFunctionDef = _deferred
    visit_Lambda = _deferred

    # ---- calls ---- #

    def visit_Call(self, node: ast.Call) -> None:
        desc = _classify_blocker(node, self.aliases)
        if desc is not None and not self._condition_wait_exempt(node):
            self.summary.blockers.append(_Blocker(
                desc=desc, held=tuple(self.held), node=node,
                module=self.ctx,
                sanctioned=self.model.site_disabled(self.ctx, node, "edl103"),
            ))
        callees = self.model.graph.resolve_call(
            node, self.info, self.local_types
        )
        if callees:
            self.summary.calls.append(_CallSite(
                call=node,
                callees=tuple(c.key for c in callees),
                held=tuple(self.held),
                node=node,
                module=self.ctx,
            ))
        self.generic_visit(node)

    def _condition_wait_exempt(self, call: ast.Call) -> bool:
        """`self._cv.wait()` where _cv is the ONLY held lock and is a
        Condition: wait releases it, so nothing stays blocked."""
        f = call.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("wait", "wait_for")):
            return False
        recv = f.value
        if not (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and self.cls is not None
        ):
            return False
        if self.locks.get(recv.attr) != "condition":
            return False
        node_name = lock_node(self.cls.name, recv.attr)
        return list(self.held) == [node_name]


class ConcurrencyModel:
    """Per-run shared state for the EDL1xx family: function summaries,
    the transitive acquire sets, the may-block closure, and the global
    lock graph. Built once per ProjectContext."""

    def __init__(self, project: ProjectContext):
        self.project = project
        self.graph: CallGraph = project.callgraph
        self._aliases: Dict[str, _ModuleAliases] = {}
        self._module_locks: Dict[str, Dict[str, str]] = {}
        self._class_locks: Dict[str, Dict[str, str]] = {}
        self.summaries: Dict[str, _FnSummary] = {}
        self._lock_kinds: Dict[str, str] = {}   # node name -> kind
        self._build_summaries()
        self.acquires_trans = self._fixpoint_acquires()
        self.may_block = self._fixpoint_may_block()
        self.edges = self._build_edges()

    # ---- caches ---- #

    def aliases(self, ctx: ModuleContext) -> _ModuleAliases:
        a = self._aliases.get(ctx.rel_path)
        if a is None:
            a = self._aliases[ctx.rel_path] = _ModuleAliases(ctx)
        return a

    def module_lock_kind(self, ctx: ModuleContext, name: str) -> Optional[str]:
        """Module-global locks: `_REG_LOCK = threading.Lock()`."""
        locks = self._module_locks.get(ctx.rel_path)
        if locks is None:
            locks = {}
            for node in ctx.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    from elasticdl_tpu.analysis.callgraph import _lock_kind

                    kind = _lock_kind(node.value)
                    if kind is not None:
                        locks[node.targets[0].id] = kind
            self._module_locks[ctx.rel_path] = locks
        return locks.get(name)

    def class_locks(self, cls: ClassInfo) -> Dict[str, str]:
        """attr -> kind for every lock the class (or its bases) owns,
        unioned with the guarded_by annotations' lock names (a guard
        must be a lock even if its construction wasn't recognized)."""
        cached = self._class_locks.get(cls.key)
        if cached is not None:
            return cached
        out = dict(self.graph.lock_attrs_of(cls))
        for c in self.graph.mro(cls):
            for lock in guarded_attrs(c.module, c.node).values():
                out.setdefault(lock, "lock")
        self._class_locks[cls.key] = out
        return out

    def site_disabled(
        self, ctx: ModuleContext, node: ast.AST, rule_key: str
    ) -> bool:
        """Is there a reviewed `# edl-lint: disable=<rule>` on this node's
        lines? Used to stop EDL103 propagation at sanctioned blockers and
        drop EDL102 edges at reviewed acquisition sites."""
        per_line, per_file = ctx._suppressions
        keys = {rule_key, "all"}
        if per_file & keys:
            return True
        start = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", start) or start
        return any(
            per_line.get(line, set()) & keys
            for line in range(start, end + 1)
        )

    # ---- summaries ---- #

    def _entry_holds(
        self, cls: Optional[ClassInfo], fn: FunctionInfo
    ) -> Tuple[str, ...]:
        """Locks a def declares it is called under — `# holds:` names and
        the `_locked` suffix — resolved against the DEFINING class's
        known locks (unresolvable names are dropped: a mixin's `# holds:
        _lock` can't name a node until a subclass owns the lock)."""
        if cls is None:
            return ()
        locks = self.class_locks(cls)
        if not locks:
            return ()
        names: Set[str] = set()
        node = fn.node
        if fn.name.endswith("_locked"):
            # "_foo_locked runs under THE lock": prefer the canonical
            # `_lock`; a class without one means every lock it owns
            names |= {"_lock"} if "_lock" in locks else set(locks)
        for line in (node.lineno, node.lineno - 1):
            m = _HOLDS_RE.search(fn.module.line_text(line))
            if m:
                names |= {
                    n.strip() for n in m.group("locks").split(",") if n.strip()
                }
        return tuple(
            lock_node(cls.name, n) for n in sorted(names) if n in locks
        )

    def _build_summaries(self) -> None:
        for key, fn in self.graph.functions.items():
            cls = None
            if fn.class_name:
                for c in self.graph.resolve_class_name(
                    fn.class_name, fn.module
                ):
                    if c.module.rel_path == fn.module.rel_path:
                        cls = c
                        break
            entry = self._entry_holds(cls, fn)
            visitor = _FunctionVisitor(self, fn, cls, entry)
            for stmt in fn.node.body:
                visitor.visit(stmt)
            self.summaries[key] = visitor.summary
            for acq in visitor.summary.acquires:
                self._lock_kinds.setdefault(acq.lock, acq.kind)

    def lock_kind(self, node_name: str) -> str:
        return self._lock_kinds.get(node_name, "lock")

    # ---- fixpoints ---- #

    def _fixpoint_acquires(self) -> Dict[str, Set[str]]:
        """Transitive closure: every lock a call to F may acquire.
        Construction-time acquisitions don't count against callers —
        `__init__` runs happens-before publication (same exemption the
        guarded-by rule grants), so constructing an object under a lock
        does not order the new object's lock after the held one."""
        acq: Dict[str, Set[str]] = {
            k: {a.lock for a in s.acquires if not a.suppressed}
            for k, s in self.summaries.items()
        }
        changed = True
        while changed:
            changed = False
            for k, s in self.summaries.items():
                cur = acq[k]
                before = len(cur)
                for c in s.calls:
                    for callee in c.callees:
                        if callee.split(".")[-1] in _CONSTRUCTION_METHODS:
                            continue
                        cur |= acq.get(callee, set())
                if len(cur) != before:
                    changed = True
        return acq

    def _fixpoint_may_block(self) -> Dict[str, Tuple[str, str]]:
        """key -> (description, witness site) for functions that may
        block. Sanctioned blockers (reviewed disable=EDL103) neither
        count locally nor propagate."""
        out: Dict[str, Tuple[str, str]] = {}
        for k, s in self.summaries.items():
            for b in s.blockers:
                if b.sanctioned:
                    continue
                site = f"{b.module.rel_path}:{b.node.lineno}"
                out[k] = (b.desc, site)
                break
        changed = True
        while changed:
            changed = False
            for k, s in self.summaries.items():
                if k in out:
                    continue
                for c in s.calls:
                    hit = next(
                        (cl for cl in c.callees
                         if cl in out
                         and cl.split(".")[-1] not in _CONSTRUCTION_METHODS),
                        None,
                    )
                    if hit is not None:
                        desc, site = out[hit]
                        callee_disp = hit.split("::")[-1]
                        out[k] = (
                            f"{desc} via {callee_disp}",
                            site,
                        )
                        changed = True
                        break
        return out

    # ---- the lock graph ---- #

    def _build_edges(self) -> Dict[Tuple[str, str], List[str]]:
        """(held, acquired) -> acquisition sites, unioned over every
        function: direct `with` nesting plus call-through acquisition
        (caller holds H, callee transitively acquires A => H -> A)."""
        edges: Dict[Tuple[str, str], List[str]] = {}

        def add(h: str, a: str, site: str) -> None:
            if h == a:
                return
            sites = edges.setdefault((h, a), [])
            if site not in sites:
                sites.append(site)

        for k, s in self.summaries.items():
            for acq in s.acquires:
                if acq.suppressed:
                    continue
                site = f"{acq.module.rel_path}:{acq.node.lineno} ({k.split('::')[-1]})"
                for h in acq.held:
                    add(h, acq.lock, site)
            for c in s.calls:
                if not c.held:
                    continue
                site = (
                    f"{c.module.rel_path}:{c.node.lineno} "
                    f"({k.split('::')[-1]} -> call)"
                )
                for callee in c.callees:
                    if callee.split(".")[-1] in _CONSTRUCTION_METHODS:
                        continue
                    for a in self.acquires_trans.get(callee, set()):
                        for h in c.held:
                            add(h, a, site)
        return edges

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the acquisition-order graph, each
        reported once in canonical rotation (same algorithm family as
        lockorder.LockOrderRecorder.cycles)."""
        edge_list = list(self.edges)
        out: List[List[str]] = []
        seen: Set[Tuple[str, ...]] = set()
        for (a, b) in edge_list:
            path = self._find_path(b, a, edge_list)
            if path is None:
                continue
            cyc = [a] + path
            nodes = cyc[:-1] if cyc[0] == cyc[-1] else cyc
            k = min(range(len(nodes)), key=lambda i: nodes[i])
            canon = tuple(nodes[k:] + nodes[:k])
            if canon not in seen:
                seen.add(canon)
                out.append(list(canon))
        return out

    @staticmethod
    def _find_path(
        src: str, dst: str, edges: List[Tuple[str, str]]
    ) -> Optional[List[str]]:
        stack = [(src, [src])]
        seen: Set[str] = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for (a, b) in edges:
                if a == node:
                    stack.append((b, path + [b]))
        return None

    def reentrant_acquires(self) -> Iterator[_Acquire]:
        """`with self.X` (or a call that re-acquires X) while X is
        already held — a self-deadlock on a plain Lock."""
        for s in self.summaries.values():
            for acq in s.acquires:
                if acq.suppressed or acq.kind != "lock":
                    continue
                if acq.lock in acq.held:
                    yield acq


def concurrency_model(project: ProjectContext) -> ConcurrencyModel:
    model = project.cache.get("concurrency")
    if model is None:
        model = ConcurrencyModel(project)
        project.cache["concurrency"] = model
    return model


# ------------------------------------------------------------------ #
# lock-graph emission (CLI --lock-graph, CI artifact, cross-check test)


def build_lock_graph(project: ProjectContext) -> Dict:
    """JSON-ready static lock-acquisition graph: nodes (with kinds),
    directed edges with their source sites, and any cycles."""
    model = concurrency_model(project)
    nodes = sorted(
        {n for e in model.edges for n in e}
        | set(model._lock_kinds)
    )
    return {
        "version": 1,
        "nodes": [
            {"name": n, "kind": model.lock_kind(n)} for n in nodes
        ],
        "edges": [
            {"from": a, "to": b, "sites": sites}
            for (a, b), sites in sorted(model.edges.items())
        ],
        "cycles": model.cycles(),
    }


def render_lock_graph_dot(graph: Dict) -> str:
    lines = ["digraph lock_order {", "  rankdir=LR;"]
    cyc_nodes = {n for c in graph["cycles"] for n in c}
    for n in graph["nodes"]:
        attrs = ' [color=red, penwidth=2]' if n["name"] in cyc_nodes else ""
        lines.append(f'  "{n["name"]}"{attrs};')
    for e in graph["edges"]:
        label = e["sites"][0].split(" ")[0] if e["sites"] else ""
        lines.append(
            f'  "{e["from"]}" -> "{e["to"]}" [label="{label}"];'
        )
    lines.append("}")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------------ #
# EDL102


@register
class LockOrderInversionRule(ProjectRule):
    """Static lock-order inversion detection.

    Builds the whole-program lock-acquisition graph: a directed edge
    A -> B means some code path acquires B while holding A — either a
    literal `with self._b:` nested inside `with self._a:`, or a call
    made under A to a function that (transitively) acquires B. Held
    sets are seeded from `with` nesting, `# holds: <lock>` declarations
    and the `_locked` method-name idiom, and propagated through the
    class/method-resolving call graph, so a cross-module inversion
    (membership -> journal in one path, journal -> membership in
    another) is caught without either file mentioning the other's lock.

    A cycle in the graph is a POTENTIAL deadlock: two threads walking
    the cycle's edges concurrently can each hold the lock the other
    wants. The runtime recorder (`analysis/lockorder.py`) proves the
    orders that executed are acyclic; this rule proves no OTHER order
    is expressible. Re-entrant acquisition of a plain (non-reentrant)
    Lock is reported by the same rule — that one needs no second
    thread to deadlock.

    Fix by acquiring in a single global order (document it where the
    locks are declared), or release before calling into the other
    component (the membership death-callback idiom). Suppress a
    reviewed-impossible edge with `# edl-lint: disable=EDL102` ON the
    acquisition site — that drops the edge from the graph (and the
    `--lock-graph` artifact) rather than just hiding a finding.
    """

    id = "EDL102"
    name = "lock-order-inversion"
    doc = (
        "cycle in the static lock-acquisition graph (interprocedural "
        "held-set propagation over `with self.<lock>:` sites, `# holds:` "
        "declarations, and the `_locked` idiom) — a potential deadlock "
        "even if no run has interleaved it yet"
    )

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        model = concurrency_model(project)
        for cycle in sorted(model.cycles()):
            yield from self._cycle_finding(model, cycle)
        for acq in model.reentrant_acquires():
            yield self.finding(
                acq.module, acq.node,
                f"re-entrant acquisition: `with` on {acq.lock} while "
                f"already holding it — self-deadlock on a "
                f"non-reentrant Lock",
            )

    def _cycle_finding(
        self, model: ConcurrencyModel, cycle: List[str]
    ) -> Iterator[Finding]:
        ring = cycle + [cycle[0]]
        legs = []
        anchor: Optional[Tuple[ModuleContext, ast.AST]] = None
        for a, b in zip(ring, ring[1:]):
            sites = model.edges.get((a, b), [])
            legs.append(f"{a} -> {b} at {sites[0] if sites else '<?>'}")
            if anchor is None:
                anchor = self._site_node(model, (a, b))
        msg = (
            "lock-order inversion: cycle "
            + " -> ".join(ring)
            + " ("
            + "; ".join(legs)
            + ")"
        )
        if anchor is not None:
            ctx, node = anchor
            yield self.finding(ctx, node, msg)

    @staticmethod
    def _site_node(
        model: ConcurrencyModel, edge: Tuple[str, str]
    ) -> Optional[Tuple[ModuleContext, ast.AST]]:
        """The AST site backing an edge's first recorded occurrence."""
        target_sites = model.edges.get(edge, [])
        if not target_sites:
            return None
        first = target_sites[0]
        for s in model.summaries.values():
            for acq in s.acquires:
                if f"{acq.module.rel_path}:{acq.node.lineno}" in first \
                        and edge[1] == acq.lock and edge[0] in acq.held:
                    return acq.module, acq.node
            for c in s.calls:
                if f"{c.module.rel_path}:{c.node.lineno}" in first \
                        and edge[0] in c.held:
                    return c.module, c.node
        return None


# ------------------------------------------------------------------ #
# EDL103


@register
class BlockingCallUnderLockRule(ProjectRule):
    """Blocking call while holding a lock, interprocedurally.

    "May block" seeds: `time.sleep`, `.wait()` (Commit / Event /
    Condition / Popen), `queue.get/put` (blocking forms), subprocess
    spawn/drain, socket I/O, `open()` / `os.fsync` / `os.fdatasync`,
    `.result()` futures, thread `.join()`, and RPC-stub calls. The
    property propagates through the call graph: a function that calls a
    may-block function may block. Any call made while a lock is held —
    `with self._lock:` nesting, a `# holds:`/`_locked` method — to a
    blocking primitive or a may-block function is flagged.

    Why it matters here: every master lock serializes gRPC handler
    threads; one fsync or RPC stalled under a lock convoys the whole
    handler pool (the journal's group-commit redesign exists precisely
    to move the fsync out from under the owner locks). EDL403 catches
    the lexical fsync-under-lock case; this rule generalizes it to
    every blocker and every call depth.

    The Condition idiom is exempt: `self._cv.wait()` while `_cv` is the
    ONLY held lock releases it (that is what Conditions are for).

    A reviewed `# edl-lint: disable=EDL103` on the BLOCKING line both
    silences the site and stops propagation — callers of a sanctioned
    blocker are not charged (the journal committer's fsync runs on a
    dedicated thread under its private file lock; every control-plane
    append routed through it must stay clean).
    """

    id = "EDL103"
    name = "blocking-call-under-lock"
    doc = (
        "call that may block (sleep / wait / queue / subprocess / "
        "socket / file I/O / RPC stub — propagated interprocedurally "
        "through the call graph) made while holding a lock: one stalled "
        "holder convoys every thread behind the lock"
    )

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        model = concurrency_model(project)
        for key, s in model.summaries.items():
            for b in s.blockers:
                if b.sanctioned or not b.held:
                    continue
                yield self.finding(
                    b.module, b.node,
                    f"blocking {b.desc} while holding "
                    f"{', '.join(b.held)}",
                )
            for c in s.calls:
                if not c.held:
                    continue
                for callee in c.callees:
                    hit = model.may_block.get(callee)
                    if hit is None:
                        continue
                    if callee.split(".")[-1] in _CONSTRUCTION_METHODS:
                        continue
                    desc, site = hit
                    yield self.finding(
                        c.module, c.node,
                        f"call to {callee.split('::')[-1]} while holding "
                        f"{', '.join(c.held)} — it may block "
                        f"({desc} at {site})",
                    )
                    break


# ------------------------------------------------------------------ #
# EDL104


@register
class GuardedStateEscapeRule(ProjectRule):
    """A guarded MUTABLE attribute's reference escaping its lock.

    EDL101 proves every touch of a `# guarded_by:` attribute happens
    under the lock; it deliberately ignores aliasing. This rule closes
    the half the reviews kept catching by hand (Autoscaler.snapshot in
    PR 14, PushQueue journaling in PR 15): inside the critical section
    the code hands out the CONTAINER ITSELF —

      - `return self._workers` / `yield self._stats`
      - `other.cache = self._members` (stored onto another object)
      - `self._last = self._doing` (aliased under a different guard)
      - `Thread(target=f, args=(self._health,))`, `q.put(self._map)`,
        `pool.submit(f, self._rows)` (captured by another thread)
      - returning a live `.keys()/.values()/.items()` view

    — after which every "guarded" access contract is void: the caller
    mutates or iterates the container with no lock at all, racing the
    next guarded writer (the snapshot-without-copy crash class).

    Take a copy INSIDE the lock instead: `dict(self._workers)`,
    `list(...)`, `sorted(...)`, `.copy()`, `copy.deepcopy(...)` all
    sanitize the escape. Scalars are exempt (rebinding an int escapes a
    value, not shared state); attributes whose constructed type can't
    be shown mutable are skipped rather than guessed.
    """

    id = "EDL104"
    name = "guarded-state-escape"
    doc = (
        "`# guarded_by:` mutable attribute returned/yielded/stored/"
        "thread-captured as a live reference (no copy inside the lock) — "
        "the lock stops meaning anything once the reference escapes"
    )

    def check_project(self, project: ProjectContext) -> Iterator[Finding]:
        for ctx in project.modules:
            for cls in ast.walk(ctx.tree):
                if isinstance(cls, ast.ClassDef):
                    yield from self._check_class(ctx, cls)

    def _check_class(
        self, ctx: ModuleContext, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        guarded = guarded_attrs(ctx, cls)
        if not guarded:
            return
        mutable = {
            attr for attr in guarded if _attr_is_mutable(ctx, cls, attr)
        }
        if not mutable:
            return
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in _CONSTRUCTION_METHODS:
                continue
            v = _EscapeVisitor(self, ctx, guarded, mutable)
            for stmt in node.body:
                v.visit(stmt)
            yield from v.findings


def _attr_is_mutable(
    ctx: ModuleContext, cls: ast.ClassDef, attr: str
) -> bool:
    """Mutability from the construction-method assignment: container
    display/constructor, or a container-typed annotation. Unknown
    types are NOT flagged (conservative)."""
    for node in ast.walk(cls):
        target = value = ann = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value, ann = node.target, node.value, node.annotation
        if not (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and target.attr == attr
        ):
            continue
        if isinstance(value, (ast.Dict, ast.List, ast.Set)):
            return True
        if isinstance(value, ast.Call):
            f = value.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else ""
            )
            if name in _MUTABLE_CTORS:
                return True
        if ann is not None and _MUTABLE_ANN_RE.search(ast.unparse(ann)):
            return True
    return False


_ESCAPE_SINK_CALLS = {"put", "submit", "put_nowait"}
_THREAD_CTORS = {"Thread", "Timer"}


class _EscapeVisitor(ast.NodeVisitor):
    """Walk one method finding guarded-container references that leave."""

    def __init__(
        self,
        rule: GuardedStateEscapeRule,
        ctx: ModuleContext,
        guarded: Dict[str, str],
        mutable: Set[str],
    ):
        self.rule = rule
        self.ctx = ctx
        self.guarded = guarded
        self.mutable = mutable
        self.aliases: Dict[str, str] = {}   # local name -> guarded attr
        self.findings: List[Finding] = []

    # nested defs/lambdas: separate escape surface, skipped (EDL101
    # already empties their held-set; chasing closures is out of scope)
    def visit_FunctionDef(self, node):  # noqa: D102
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # ---- alias tracking + stores ---- #

    def _guarded_ref(self, expr: ast.AST) -> Optional[str]:
        """Guarded-attr name if expr is a live reference to it: the
        attribute itself, a tracked local alias, or a .keys/.values/
        .items() view of either."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in self.mutable
        ):
            return expr.attr
        if isinstance(expr, ast.Name) and expr.id in self.aliases:
            return self.aliases[expr.id]
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr in ("keys", "values", "items")
            and not expr.args
        ):
            return self._guarded_ref(expr.func.value)
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        ref = self._guarded_ref(node.value)
        for target in node.targets:
            if ref is None:
                break
            if isinstance(target, ast.Name):
                # alias into a local: not yet an escape, but remembered
                self.aliases[target.id] = ref
            elif isinstance(target, ast.Attribute):
                if (
                    isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    if self.guarded.get(target.attr) == self.guarded.get(ref):
                        continue   # same guard domain: still covered
                    self._escape(
                        node, ref,
                        f"aliased into self.{target.attr} (guard "
                        f"'{self.guarded.get(target.attr, 'none')}' != "
                        f"'{self.guarded[ref]}')",
                    )
                else:
                    self._escape(
                        node, ref,
                        f"stored onto {_dotted_tail(target.value) or 'another object'}"
                        f".{target.attr}",
                    )
            elif isinstance(target, ast.Subscript):
                self._escape(node, ref, "stored into a container")
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            ref = self._guarded_ref(node.value)
            if ref is not None:
                self._escape(node, ref, "returned as a live reference")
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield) -> None:
        if node.value is not None:
            ref = self._guarded_ref(node.value)
            if ref is not None:
                self._escape(node, ref, "yielded as a live reference")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        sinky = (
            isinstance(f, ast.Attribute) and f.attr in _ESCAPE_SINK_CALLS
        ) or (
            isinstance(f, ast.Name) and f.id in _THREAD_CTORS
        ) or (
            isinstance(f, ast.Attribute) and f.attr in _THREAD_CTORS
        )
        if sinky:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                refs = []
                ref = self._guarded_ref(arg)
                if ref is not None:
                    refs.append((arg, ref))
                elif isinstance(arg, (ast.Tuple, ast.List)):
                    for el in arg.elts:
                        r = self._guarded_ref(el)
                        if r is not None:
                            refs.append((el, r))
                for el, r in refs:
                    self._escape(
                        node, r,
                        "handed to another thread "
                        f"({_dotted_tail(f) or 'sink'})",
                    )
        self.generic_visit(node)

    def _escape(self, node: ast.AST, attr: str, how: str) -> None:
        self.findings.append(
            self.rule.finding(
                self.ctx, node,
                f"self.{attr} (guarded_by {self.guarded[attr]}) escapes: "
                f"{how} — copy inside the lock "
                f"(dict()/list()/sorted()/.copy()) instead",
            )
        )
