"""EDL101 guarded-by: annotation-driven lock discipline for class state.

Convention (enforced here, documented in docs/development.md):

- Annotate a shared attribute at its `__init__` assignment:

      self._workers: Dict[int, WorkerInfo] = {}   # guarded_by: _lock

- Every other read/write of `self._workers` inside the class must then
  happen either lexically under `with self._lock:` (aliases via
  `with self._lock as l:` count; `self._lock.acquire()` does NOT — the
  release pairing isn't checkable), or inside a method that asserts it is
  called with the lock held:

      * a `_locked`-suffixed method name (the codebase's existing idiom), or
      * a `# holds: _lock` comment on the `def` line or the comment line
        directly above it.

- `__init__` is exempt (construction happens-before publication), as are
  other methods listed in _CONSTRUCTION_METHODS.

Nested functions and lambdas defined inside a method run later, on
whatever thread calls them — they get an EMPTY held-set even when defined
under the lock. If a closure really is only called under the lock,
suppress with `# edl-lint: disable=EDL101` at the access.

This is deliberately a LEXICAL checker, not an escape analysis: it can be
fooled by aliasing (`w = self._workers` under the lock, used after).
It exists to catch the common failure — a new method reading a guarded
map without the lock — at review time, not to prove the program race-free.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set

from elasticdl_tpu.analysis.core import Finding, ModuleContext, Rule, register

_GUARDED_RE = re.compile(
    r"self\.(?P<attr>\w+)\s*(?::[^=]*)?=.*#\s*guarded_by:\s*(?P<lock>\w+)"
)
# comment-only line form: `# guarded_by: _lock` annotating the NEXT line's
# `self.attr = ...` (used when the assignment line is already full)
_GUARDED_ABOVE_RE = re.compile(r"^\s*#\s*guarded_by:\s*(?P<lock>\w+)\s*$")
_SELF_ASSIGN_RE = re.compile(r"^\s*self\.(?P<attr>\w+)\s*(?::[^=]*)?=")
_HOLDS_RE = re.compile(r"#\s*holds:\s*(?P<locks>[\w, ]+)")

#: methods that run before the object is visible to other threads
_CONSTRUCTION_METHODS = {"__init__", "__new__", "__post_init__"}


def guarded_attrs(ctx: ModuleContext, cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock name, from `guarded_by:` annotation comments in the
    class's construction methods (shared by EDL101 and EDL402)."""
    out: Dict[str, str] = {}
    end = cls.end_lineno or cls.lineno
    for line in range(cls.lineno, end + 1):
        # only annotations inside construction methods define guards
        # (an annotation elsewhere would be ambiguous about intent)
        qual = ctx.qualname_at(line)
        if qual.split(".")[-1] not in _CONSTRUCTION_METHODS:
            continue
        m = _GUARDED_RE.search(ctx.line_text(line))
        if m:
            out[m.group("attr")] = m.group("lock")
            continue
        m = _GUARDED_ABOVE_RE.match(ctx.line_text(line))
        if m:
            nxt = _SELF_ASSIGN_RE.match(ctx.line_text(line + 1))
            if nxt:
                out[nxt.group("attr")] = m.group("lock")
    return out


def _with_held_locks(node: ast.With) -> Set[str]:
    """Lock attribute names this `with` statement acquires (self.X only)."""
    held: Set[str] = set()
    for item in node.items:
        expr = item.context_expr
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            held.add(expr.attr)
    return held


def method_held_locks(
    ctx: ModuleContext, node: ast.FunctionDef, class_locks: Set[str]
) -> Set[str]:
    """Locks a method declares it is called under (shared with EDL402)."""
    held: Set[str] = set()
    if node.name.endswith("_locked"):
        # the codebase idiom: `_foo_locked` is only called under the lock
        held |= class_locks
    for line in (node.lineno, node.lineno - 1):
        m = _HOLDS_RE.search(ctx.line_text(line))
        if m:
            held |= {
                name.strip() for name in m.group("locks").split(",") if name.strip()
            }
    return held


class _AccessVisitor(ast.NodeVisitor):
    """Walk one method body tracking the lexically-held lock set."""

    def __init__(
        self,
        rule: "GuardedByRule",
        ctx: ModuleContext,
        guarded: Dict[str, str],
        held: Set[str],
    ):
        self.rule = rule
        self.ctx = ctx
        self.guarded = guarded
        self.held = set(held)
        self.findings: List[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = _with_held_locks(node)
        for item in node.items:
            self.visit(item.context_expr)   # the lock expression itself
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        saved = set(self.held)
        self.held |= acquired
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    def _visit_deferred(self, node: ast.AST) -> None:
        """Nested defs/lambdas execute later: empty held-set inside."""
        saved = set(self.held)
        self.held = set()
        self.generic_visit(node)
        self.held = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_deferred(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_deferred(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_deferred(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.guarded
        ):
            lock = self.guarded[node.attr]
            if lock not in self.held:
                kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                self.findings.append(
                    self.rule.finding(
                        self.ctx,
                        node,
                        f"{kind} of self.{node.attr} (guarded_by {lock}) "
                        f"outside `with self.{lock}`",
                    )
                )
        self.generic_visit(node)


@register
class GuardedByRule(Rule):
    id = "EDL101"
    name = "guarded-by"
    doc = (
        "access to a `# guarded_by: <lock>` attribute outside "
        "`with self.<lock>` (or a method annotated/`_locked`-named as "
        "holding it)"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = guarded_attrs(ctx, cls)
            if not guarded:
                continue
            class_locks = set(guarded.values())
            for node in cls.body:
                yield from self._check_function(ctx, node, guarded, class_locks)

    def _check_function(
        self,
        ctx: ModuleContext,
        node: ast.AST,
        guarded: Dict[str, str],
        class_locks: Set[str],
    ) -> Iterator[Finding]:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if node.name in _CONSTRUCTION_METHODS:
            return
        held = method_held_locks(ctx, node, class_locks)
        visitor = _AccessVisitor(self, ctx, guarded, held)
        for stmt in node.body:
            visitor.visit(stmt)
        yield from visitor.findings
