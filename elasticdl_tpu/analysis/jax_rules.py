"""EDL2xx: JAX performance/correctness hazards.

These encode the ways a JAX hot path silently loses the throughput the
paper claims (SURVEY §3.3, docs/performance.md): a host sync per batch
re-serializes the device pipeline; a jit built per call recompiles every
step; a `self.` mutation under trace leaks tracers; unordered iteration
changes pytree structure between processes (distinct compiled programs —
a cohort deadlock in SPMD mode).

EDL201 host-sync-in-hot-loop
    `float()/int()/bool()/.item()/np.asarray()/np.array()/jax.device_get()`
    lexically inside a loop that dispatches device work (a call to one of
    the Trainer step/many entry points). These force the device queue to
    drain per iteration. Some syncs are the point (loss read-back that
    times the step, mask-based record accounting) — those carry
    `# edl-lint: disable=EDL201` with their justification.

EDL202 jit-cache-churn
    `jax.jit(...)` called inside a loop, or a `jax.jit(...)(...)`
    immediate call: both build a fresh jitted callable per execution, so
    XLA's compile cache keys on a new function object every time.
    Cache the jitted callable (module/instance attribute) instead.

EDL203 tracer-leak
    assignment to `self.*` (or a nonlocal/global) inside a function that
    is jitted (decorated, or passed to `jax.jit` in the same module).
    Under trace this stores a Tracer into long-lived state; it escapes
    the trace and fails — or worse, silently retraces — later.

EDL204 unordered-iteration
    iteration over a `set` (literal, comprehension, or `set(...)` call)
    in a `for`/comprehension. Set order varies across processes
    (PYTHONHASHSEED), so any pytree/spec built from it can differ
    between cohort members. Sort first.

EDL205 unkeyed-jit-in-rescale-path
    `jax.jit(...)` called inside a reform/rescale/resize/handoff code
    path without going through the executable cache
    (training/compile_cache.py get_or_build/store_aot). The rescale fast
    path exists to make recovery compile-free; a fresh jit built during
    recovery keys XLA's cache on a new function object and pays the full
    re-trace the cache was built to avoid. Route it through the cache
    (the builder lambda handed to `get_or_build` is exempt).

EDL206 per-row-embedding-rpc-in-hot-loop
    an embedding-tier `.pull(...)`/`.push(...)` call issued PER ID —
    lexically inside a nested loop (or comprehension) within a
    step-dispatch hot loop (EDL201's definition). The tier client
    dedupes the whole batch and issues ONE batched call per shard; a
    per-row call re-creates the reference's per-key PS traffic, paying a
    transport round trip per id instead of per shard. Receivers are
    matched by name (tier/client/emb/transport/store) so unrelated
    `.push` methods stay quiet; one batched call directly in the
    dispatch loop body is the sanctioned shape.

EDL207 blocking-pull-with-pipeline-available
    a blocking tier `.pull(...)`/`.pull_unique(...)` DIRECTLY in the
    step-dispatch hot loop (EDL201/EDL206's hot-loop definition) while
    a pull pipeline is available in the enclosing scope — a parameter
    or binding named `*pipeline(s)`, or anything constructed from a
    `*PullPipeline(...)` ctor. EDL206's sanctioned shape (one batched
    call in the loop body) becomes the anti-pattern the moment the
    overlap machinery is in hand: the blocking pull serializes the
    owner RPC behind the step it could have hidden under. Route it
    through `pipeline.submit()` ahead / `pipeline.get()` in the loop
    (embedding/tier.EmbeddingPullPipeline, or
    EmbeddingTierSession.run's windowed form). `.push` stays exempt —
    writes are the step's own output and cannot be issued ahead.

EDL209 uncoalesced-per-table-pull
    a tier `.pull(...)`/`.pull_unique(...)` issued once PER TABLE — an
    inner loop within a step-dispatch hot loop (EDL201/EDL206's
    definition) whose body passes the loop variable into the tier
    call. Each iteration pays a full owner round trip for one table's
    ids; `pull_unique_multi({table: ids, ...})` fuses every table's
    misses into ONE wire call per owner (EmbeddingPullMulti), and the
    owner's full watermark set piggybacks on the response for free.
    EDL206 usually co-fires on the same call (it is also a nested-loop
    tier call); EDL209 names the fix.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from elasticdl_tpu.analysis.core import Finding, ModuleContext, Rule, register

#: calls that dispatch device work — a loop containing one is a hot loop
_DISPATCH_METHODS = {
    "train_step", "train_many", "eval_step", "eval_many",
    "predict_step", "predict_many", "apply_gradients",
}

#: builtin conversions that force a host sync when fed a device value
_SYNC_BUILTINS = {"float", "int", "bool"}


def _is_jax_jit(func: ast.AST) -> bool:
    """`jax.jit`, bare `jit`, or `partial(jax.jit, ...)`."""
    if isinstance(func, ast.Attribute) and func.attr == "jit":
        return True
    if isinstance(func, ast.Name) and func.id == "jit":
        return True
    if isinstance(func, ast.Call):
        f = func.func
        partial = (
            isinstance(f, ast.Name) and f.id == "partial"
        ) or (isinstance(f, ast.Attribute) and f.attr == "partial")
        if partial and func.args and _is_jax_jit(func.args[0]):
            return True
    return False


def _called_attr_names(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            out.add(sub.func.attr)
    return out


@register
class HostSyncInHotLoopRule(Rule):
    id = "EDL201"
    name = "host-sync-in-hot-loop"
    doc = (
        "host-device sync (float/int/bool/.item/np.asarray/device_get) "
        "inside a loop that dispatches device steps"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        reported: Set[int] = set()   # a call nested in two loops fires once
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = list(node.body) + list(node.orelse)
            called = set()
            for stmt in body:
                called |= _called_attr_names(stmt)
            if not (called & _DISPATCH_METHODS):
                continue
            for stmt in body:
                yield from self._scan(ctx, stmt, reported)

    def _scan(
        self, ctx: ModuleContext, node: ast.AST, reported: Set[int]
    ) -> Iterator[Finding]:
        for sub in ast.walk(node):
            if id(sub) in reported:
                continue
            reported.add(id(sub))
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if (
                isinstance(func, ast.Name)
                and func.id in _SYNC_BUILTINS
                and sub.args
                and not isinstance(sub.args[0], ast.Constant)
            ):
                yield self.finding(
                    ctx, sub,
                    f"{func.id}() in a dispatch loop forces a host sync "
                    "per iteration; accumulate on device or hoist",
                )
            elif isinstance(func, ast.Attribute) and func.attr == "item":
                yield self.finding(
                    ctx, sub,
                    ".item() in a dispatch loop forces a host sync per "
                    "iteration; accumulate on device or hoist",
                )
            elif isinstance(func, ast.Attribute) and func.attr in (
                "asarray", "array",
            ) and isinstance(func.value, ast.Name) and func.value.id in (
                "np", "numpy",
            ):
                yield self.finding(
                    ctx, sub,
                    f"np.{func.attr}() in a dispatch loop copies device "
                    "data to host per iteration; accumulate on device or hoist",
                )
            elif isinstance(func, ast.Attribute) and func.attr == "device_get":
                yield self.finding(
                    ctx, sub,
                    "jax.device_get() in a dispatch loop forces a host "
                    "sync per iteration; accumulate on device or hoist",
                )


@register
class JitCacheChurnRule(Rule):
    id = "EDL202"
    name = "jit-cache-churn"
    doc = (
        "jax.jit built per call (inside a loop, or immediately invoked) — "
        "recompiles every execution; cache the jitted callable"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        loops = [
            n for n in ast.walk(ctx.tree) if isinstance(n, (ast.For, ast.While))
        ]
        seen: Set[int] = set()
        for loop in loops:
            for sub in ast.walk(loop):
                if (
                    isinstance(sub, ast.Call)
                    and _is_jax_jit(sub.func)
                    and id(sub) not in seen
                ):
                    seen.add(id(sub))
                    yield self.finding(
                        ctx, sub,
                        "jax.jit inside a loop builds a fresh callable per "
                        "iteration (compile-cache miss every time); hoist "
                        "and cache it",
                    )
        for sub in ast.walk(ctx.tree):
            # jax.jit(f)(args): the jitted callable dies after one call
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Call)
                and _is_jax_jit(sub.func.func)
                and id(sub.func) not in seen
            ):
                yield self.finding(
                    ctx, sub,
                    "jax.jit(...)(...) immediate call discards the jitted "
                    "callable — every execution recompiles; cache it on the "
                    "module/instance",
                )


@register
class TracerLeakRule(Rule):
    id = "EDL203"
    name = "tracer-leak"
    doc = (
        "assignment to self.*/nonlocal/global inside a jitted function — "
        "stores a Tracer into long-lived state"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        jitted = self._jitted_functions(ctx)
        for fn in jitted:
            yield from self._scan_body(ctx, fn)

    def _jitted_functions(self, ctx: ModuleContext) -> List[ast.AST]:
        out: List[ast.AST] = []
        by_name = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, node)
                if any(_is_jax_jit(d) or (
                    isinstance(d, ast.Call) and _is_jax_jit(d.func)
                ) for d in node.decorator_list):
                    out.append(node)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_jax_jit(node.func) and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Lambda):
                    out.append(arg)
                elif isinstance(arg, ast.Name) and arg.id in by_name:
                    out.append(by_name[arg.id])
        return out

    def _scan_body(self, ctx: ModuleContext, fn: ast.AST) -> Iterator[Finding]:
        body = getattr(fn, "body", None)
        if not isinstance(body, list):
            return  # Lambda: a single expression can hold no assignments
        declared: Set[str] = set()
        for node in body:
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Nonlocal, ast.Global)):
                    declared |= set(sub.names)
        for node in body:
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            yield self.finding(
                                ctx, sub,
                                f"assignment to self.{t.attr} inside a jitted "
                                "function stores a Tracer into long-lived "
                                "state; return it instead",
                            )
                        elif isinstance(t, ast.Name) and t.id in declared:
                            yield self.finding(
                                ctx, sub,
                                f"assignment to nonlocal/global {t.id!r} "
                                "inside a jitted function leaks a Tracer out "
                                "of the trace; return it instead",
                            )


#: function names that ARE the rescale/recovery path — a compile here is
#: paid at the worst possible time (mid-recovery), so it must be cache-keyed
_RESCALE_PATH = re.compile(r"reform|rescale|resize|handoff", re.IGNORECASE)

#: executable-cache entry points whose builder arguments legitimately
#: construct the jit being cached
_CACHE_BUILDERS = {"get_or_build", "store_aot", "cached_jit"}


@register
class UnkeyedJitInRescalePathRule(Rule):
    id = "EDL205"
    name = "unkeyed-jit-in-rescale-path"
    doc = (
        "jax.jit built inside a reform/rescale/resize/handoff code path "
        "without the executable cache — recovery pays a fresh re-trace the "
        "rescale fast path exists to avoid"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        reported: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _RESCALE_PATH.search(node.name):
                continue
            # anything under a cache entry point (the builder closure handed
            # to get_or_build/store_aot) is the sanctioned construction site
            exempt: Set[int] = set()
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _CACHE_BUILDERS
                ):
                    for inner in ast.walk(sub):
                        exempt.add(id(inner))
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and _is_jax_jit(sub.func)
                    and id(sub) not in exempt
                    and id(sub) not in reported
                ):
                    reported.add(id(sub))
                    yield self.finding(
                        ctx, sub,
                        f"jax.jit inside rescale-path function "
                        f"{node.name!r} defeats the executable cache — "
                        "recovery recompiles; route it through "
                        "compile_cache.get_or_build",
                    )


#: receiver names that mark a call as embedding-TIER traffic (the rule
#: must not fire on unrelated `.push` methods — a stack's push, say)
_TIER_RECEIVER = re.compile(r"tier|client|emb|transport|store", re.IGNORECASE)


def _tier_call(node: ast.AST) -> Optional[str]:
    """'pull'/'pull_unique'/'push' when `node` is an embedding-tier
    data-plane call."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("pull", "pull_unique", "push")):
        return None
    recv = node.func.value
    names = []
    while isinstance(recv, ast.Attribute):
        names.append(recv.attr)
        recv = recv.value
    if isinstance(recv, ast.Name):
        names.append(recv.id)
    if any(_TIER_RECEIVER.search(n) for n in names):
        return node.func.attr
    return None


@register
class PerRowEmbeddingRpcRule(Rule):
    id = "EDL206"
    name = "per-row-embedding-rpc-in-hot-loop"
    doc = (
        "embedding-tier pull/push issued per id (nested loop or "
        "comprehension) inside a step-dispatch hot loop — a transport "
        "round trip per row; dedupe the batch and issue one call per shard"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        reported: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = list(node.body) + list(node.orelse)
            called = set()
            for stmt in body:
                called |= _called_attr_names(stmt)
            if not (called & _DISPATCH_METHODS):
                # shares EDL201's hot-loop definition: only loops that
                # dispatch device steps are in scope
                continue
            if any(
                isinstance(n, (ast.For, ast.While))
                and _called_attr_names(n) & _DISPATCH_METHODS
                for stmt in body for n in ast.walk(stmt)
            ):
                # an INNER loop is the real dispatch loop (epoch loop
                # around a step loop): scan at that depth, or a batched
                # call in the step loop's own body would read as
                # "nested" relative to the epoch loop
                continue
            for stmt in body:
                yield from self._scan(ctx, stmt, reported)

    def _scan(
        self, ctx: ModuleContext, node: ast.AST, reported: Set[int]
    ) -> Iterator[Finding]:
        """Flag tier calls nested one loop (or comprehension) deeper than
        the dispatch loop's own body — the per-id shape. A tier call
        sitting directly in the dispatch body is the batched idiom."""
        for sub in ast.walk(node):
            inner: Iterator[ast.AST] = ()
            if isinstance(sub, (ast.For, ast.While)):
                inner = (n for s in (list(sub.body) + list(sub.orelse))
                         for n in ast.walk(s))
            elif isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                                  ast.GeneratorExp)):
                inner = ast.walk(sub)
            for cand in inner:
                what = _tier_call(cand)
                if what is None or id(cand) in reported:
                    continue
                reported.add(id(cand))
                yield self.finding(
                    ctx, cand,
                    f"embedding tier .{what}() per id inside the "
                    "step-dispatch hot loop pays a transport round trip "
                    "per row; dedupe the batch and issue one batched "
                    "call per shard (tier.EmbeddingTierClient does this)",
                )


#: a binding that makes the pull pipeline "available in scope":
#: parameters/assignments named like the thing, or anything constructed
#: from a *PullPipeline(...) ctor
_PIPELINE_NAME = re.compile(r"(^|_)pipelines?$", re.IGNORECASE)
_PIPELINE_CTOR = re.compile(r"PullPipeline")


def _scope_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk one lexical scope: never descends into nested function
    defs (they are their own scopes — a pipeline bound in a helper must
    not police its caller)."""
    stack = [scope]
    while stack:
        node = stack.pop()
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not scope):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _pipeline_in_scope(scope: ast.AST) -> bool:
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = scope.args
        params = (list(getattr(a, "posonlyargs", ())) + list(a.args)
                  + list(a.kwonlyargs))
        if a.vararg:
            params.append(a.vararg)
        if a.kwarg:
            params.append(a.kwarg)
        if any(_PIPELINE_NAME.search(p.arg) for p in params):
            return True
    for sub in _scope_nodes(scope):
        if isinstance(sub, ast.Assign):
            v = sub.value
            if isinstance(v, ast.Call):
                f = v.func
                name = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else "")
                if _PIPELINE_CTOR.search(name):
                    return True
            for t in sub.targets:
                if isinstance(t, ast.Name) and _PIPELINE_NAME.search(t.id):
                    return True
    return False


def _direct_body_calls(stmts) -> Iterator[ast.Call]:
    """Calls at the loop's OWN depth: nested loops/comprehensions are
    EDL206's territory, nested defs their own scope."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.For, ast.While, ast.ListComp,
                             ast.SetComp, ast.DictComp, ast.GeneratorExp,
                             ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class BlockingPullWithPipelineRule(Rule):
    id = "EDL207"
    name = "blocking-pull-with-pipeline-available"
    doc = (
        "blocking tier .pull/.pull_unique in the step-dispatch hot loop "
        "while a pull pipeline is in scope — the owner RPC serializes "
        "behind compute it could overlap; route it through "
        "pipeline.submit()/get()"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        reported: Set[int] = set()
        for scope in scopes:
            if not _pipeline_in_scope(scope):
                continue
            for node in _scope_nodes(scope):
                if not isinstance(node, (ast.For, ast.While)):
                    continue
                loop_body = list(node.body) + list(node.orelse)
                called = set()
                for stmt in loop_body:
                    called |= _called_attr_names(stmt)
                if not (called & _DISPATCH_METHODS):
                    # shares EDL201/EDL206's hot-loop definition
                    continue
                if any(
                    isinstance(n, (ast.For, ast.While))
                    and _called_attr_names(n) & _DISPATCH_METHODS
                    for stmt in loop_body for n in ast.walk(stmt)
                ):
                    # an INNER loop is the real dispatch loop (epoch
                    # wrapper): scan at that depth (EDL206's scoping)
                    continue
                for cand in _direct_body_calls(loop_body):
                    what = _tier_call(cand)
                    if what in (None, "push") or id(cand) in reported:
                        # pushes are the step's own OUTPUT — they cannot
                        # be issued ahead of the compute that makes them
                        continue
                    reported.add(id(cand))
                    yield self.finding(
                        ctx, cand,
                        f"blocking tier .{what}() in the step-dispatch "
                        "hot loop while a pull pipeline is in scope: the "
                        "owner RPC serializes behind compute it could "
                        "overlap — submit() the next batch ahead and "
                        "get() here (EmbeddingPullPipeline)",
                    )


def _target_names(target: ast.AST) -> Set[str]:
    """Names bound by a For target (`for t in ...`, `for t, ids in ...`)."""
    names: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            names.add(n.id)
    return names


@register
class UncoalescedPerTablePullRule(Rule):
    id = "EDL209"
    name = "uncoalesced-per-table-pull"
    doc = (
        "tier .pull/.pull_unique issued once per table (inner loop over "
        "table names inside a step-dispatch hot loop) — one owner round "
        "trip per table; pull_unique_multi fuses every table into one "
        "wire call per owner"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        reported: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = list(node.body) + list(node.orelse)
            called = set()
            for stmt in body:
                called |= _called_attr_names(stmt)
            if not (called & _DISPATCH_METHODS):
                # shares EDL201/EDL206's hot-loop definition
                continue
            if any(
                isinstance(n, (ast.For, ast.While))
                and _called_attr_names(n) & _DISPATCH_METHODS
                for stmt in body for n in ast.walk(stmt)
            ):
                # an INNER loop is the real dispatch loop (epoch wrapper)
                continue
            for stmt in body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.For):
                        continue
                    loop_vars = _target_names(sub.target)
                    if not loop_vars:
                        continue
                    yield from self._scan(ctx, sub, loop_vars, reported)

    def _scan(
        self, ctx: ModuleContext, loop: ast.For, loop_vars: Set[str],
        reported: Set[int],
    ) -> Iterator[Finding]:
        """Flag pull/pull_unique in the inner loop's DIRECT body that
        receive the loop variable — the per-table shape. (Deeper
        nesting re-enters check() via the outer walk; pushes are the
        step's own output and are EDL206's concern.)"""
        for cand in _direct_body_calls(list(loop.body)
                                       + list(loop.orelse)):
            what = _tier_call(cand)
            if what in (None, "push") or id(cand) in reported:
                continue
            args = list(cand.args) + [kw.value for kw in cand.keywords]
            if not any(
                isinstance(n, ast.Name) and n.id in loop_vars
                for a in args for n in ast.walk(a)
            ):
                continue
            reported.add(id(cand))
            yield self.finding(
                ctx, cand,
                f"tier .{what}() once per table in the step-dispatch "
                "hot loop pays one owner round trip per table; fuse "
                "the batch into pull_unique_multi({table: ids, ...}) — "
                "one EmbeddingPullMulti wire call per owner, with the "
                "owner's watermarks piggybacked",
            )


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
    return False


@register
class UnorderedIterationRule(Rule):
    id = "EDL204"
    name = "unordered-iteration"
    doc = (
        "iterating a set: order varies across processes (hash seed), so "
        "pytrees/specs built from it differ between cohort members"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            iters: List[ast.AST] = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                iters.extend(gen.iter for gen in node.generators)
            for it in iters:
                if _is_set_expr(it):
                    yield self.finding(
                        ctx, it,
                        "iteration over a set has process-dependent order; "
                        "wrap in sorted() before building pytrees or specs",
                    )
