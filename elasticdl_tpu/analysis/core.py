"""edl-lint framework: findings, suppressions, baseline, runner.

Design constraints that shaped this:

- AST-only, stdlib-only. The lint must run anywhere the package imports
  (CI images without dev extras, the TPU sandbox), so no libcst/ruff
  plugin machinery — `ast` + `end_lineno` (py3.8+) is enough for every
  rule here.
- Findings fingerprint WITHOUT line numbers (rule + file + enclosing
  def/class + message), so the checked-in baseline survives unrelated
  edits above a tolerated finding. Two identical findings in one scope
  get an occurrence suffix to stay distinct.
- Suppressions are per-line (`# edl-lint: disable=EDL201` on the line or
  on a comment-only line directly above) or per-file
  (`# edl-lint: disable-file=EDL201`). Rule ids and slugs both work.
  A suppression is a reviewed decision; the baseline is tolerated debt —
  new code should never add baseline entries.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

#: files never linted (generated code has no style to enforce)
EXCLUDED_FILES = {"elasticdl_tpu_pb2.py"}

#: the directive may sit anywhere in a comment ("… reason: edl-lint:
#: disable=EDL201"), so justification prose and directive share a line
_DIRECTIVE_RE = re.compile(
    r"#.*?edl-lint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete source location."""

    rule: str       # id, e.g. "EDL301"
    name: str       # slug, e.g. "bare-master-stub"
    path: str       # relative, forward-slash path
    line: int
    col: int
    message: str
    context: str = ""   # innermost enclosing "Class.method" (or "<module>")
    # last line of the flagged node: a suppression anywhere in [line,
    # end_line] silences it (an `except:` finding is suppressible from its
    # `pass` body line). NOT part of the fingerprint.
    end_line: int = 0

    def fingerprint(self) -> str:
        """Line-independent identity used by the baseline."""
        return f"{self.rule}:{self.path}:{self.context}:{self.message}"

    def render(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} ({self.name}) {self.message}{ctx}"


class Rule:
    """Base class: subclasses set `id`, `name`, `doc` and yield Findings."""

    id: str = ""
    name: str = ""
    doc: str = ""

    def check(self, ctx: "ModuleContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, ctx: "ModuleContext", node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule=self.id,
            name=self.name,
            path=ctx.rel_path,
            line=line,
            col=col,
            message=message,
            context=ctx.qualname_at(line),
            end_line=getattr(node, "end_lineno", line) or line,
        )


class ProjectRule(Rule):
    """A rule that needs the WHOLE parsed tree (call graph, cross-module
    state) instead of one module at a time. Subclasses implement
    `check_project`; suppression/baseline/CLI machinery is shared — each
    Finding is attributed to its module and suppressible there like any
    per-module finding."""

    def check(self, ctx: "ModuleContext") -> Iterator[Finding]:
        return iter(())

    def check_project(self, project: "ProjectContext") -> Iterator[Finding]:
        raise NotImplementedError


class ProjectContext:
    """Every parsed module of one run, plus shared lazily-built analyses.

    The tree is parsed ONCE (the same ModuleContexts the per-module rules
    saw); the call graph and any heavier shared models are built on first
    use and memoized in `cache`, so N project rules pay for one build."""

    def __init__(self, modules: Sequence["ModuleContext"]):
        self.modules = list(modules)
        self.by_path: Dict[str, "ModuleContext"] = {
            m.rel_path: m for m in self.modules
        }
        self.cache: Dict[str, object] = {}
        self._callgraph = None

    @property
    def callgraph(self):
        if self._callgraph is None:
            from elasticdl_tpu.analysis.callgraph import CallGraph

            self._callgraph = CallGraph(self.modules)
        return self._callgraph

    def suppressed(self, finding: Finding) -> bool:
        ctx = self.by_path.get(finding.path)
        return ctx.suppressed(finding) if ctx is not None else False


class ModuleContext:
    """One parsed module plus the lookups every rule needs."""

    def __init__(self, path: str, source: str, rel_path: Optional[str] = None):
        self.path = path
        self.rel_path = (rel_path or path).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._suppressions = self._parse_suppressions()
        self._scopes = self._collect_scopes()

    # -------------------------------------------------------------- #
    # suppressions

    def _parse_suppressions(self) -> Tuple[Dict[int, Set[str]], Set[str]]:
        per_line: Dict[int, Set[str]] = {}
        per_file: Set[str] = set()
        pending: Set[str] = set()   # from a comment-only line, applies below
        for i, text in enumerate(self.lines, start=1):
            stripped = text.strip()
            m = _DIRECTIVE_RE.search(text)
            rules: Set[str] = set()
            if m:
                rules = {
                    r.strip().lower() for r in m.group(2).split(",") if r.strip()
                }
                if m.group(1) == "disable-file":
                    per_file |= rules
                    rules = set()
            if stripped.startswith("#"):
                # comment-only line: carry the directive to the next code line
                pending |= rules
                continue
            line_rules = rules | pending
            pending = set()
            if line_rules:
                per_line[i] = line_rules
        return per_line, per_file

    def suppressed(self, finding: Finding) -> bool:
        per_line, per_file = self._suppressions
        keys = {finding.rule.lower(), finding.name.lower(), "all"}
        if per_file & keys:
            return True
        last = max(finding.line, finding.end_line or finding.line)
        return any(
            per_line.get(line, set()) & keys
            for line in range(finding.line, last + 1)
        )

    # -------------------------------------------------------------- #
    # scope lookup

    def _collect_scopes(self) -> List[Tuple[int, int, str]]:
        scopes: List[Tuple[int, int, str]] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    scopes.append(
                        (child.lineno, child.end_lineno or child.lineno, qual)
                    )
                    visit(child, qual)
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        return scopes

    def qualname_at(self, line: int) -> str:
        """Innermost def/class enclosing `line` ("<module>" if none)."""
        best = "<module>"
        best_span = None
        for start, end, qual in self._scopes:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


# ------------------------------------------------------------------ #
# rule registry

_RULES: List[Rule] = []


def register(rule_cls: type) -> type:
    _RULES.append(rule_cls())
    return rule_cls


def all_rules() -> List[Rule]:
    """Every registered rule (importing the rule modules registers them)."""
    # imported lazily so `core` has no import cycle with the rule modules
    from elasticdl_tpu.analysis import (  # noqa: F401
        concurrency,
        elasticity_rules,
        jax_rules,
        locks,
        observability_rules,
        rpc_rules,
    )

    return list(_RULES)


def select_rules(
    rules: Sequence[Rule], select: Optional[Set[str]]
) -> List[Rule]:
    """Filter by id, slug, or FAMILY PREFIX: `EDL1` selects every EDL1xx
    rule (`EDL` selects all). Matching is case-insensitive."""
    if not select:
        return list(rules)
    wanted = {s.lower() for s in select}
    out: List[Rule] = []
    for r in rules:
        rid = r.id.lower()
        if rid in wanted or r.name.lower() in wanted:
            out.append(r)
            continue
        if any(
            re.fullmatch(r"edl\d{0,2}", w) and rid.startswith(w)
            for w in wanted
        ):
            out.append(r)
    return out


# ------------------------------------------------------------------ #
# baseline

def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint -> justification. Missing file = empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", [])
    out: Dict[str, str] = {}
    for e in entries:
        out[e["fingerprint"]] = e.get("justification", "")
    return out


def _suffixed_fingerprints(findings: Sequence[Finding]) -> List[str]:
    """Fingerprints with an occurrence suffix disambiguating repeats (two
    identical findings in one scope must not collapse to one baseline
    entry). Deterministic given the runner's (path, line, col, rule) sort
    order, so write_baseline and run_analysis agree."""
    seen: Dict[str, int] = {}
    out: List[str] = []
    for f in findings:
        fp = f.fingerprint()
        n = seen.get(fp, 0)
        seen[fp] = n + 1
        out.append(fp if n == 0 else f"{fp}#{n}")
    return out


def prune_baseline(path: str, stale: Sequence[str]) -> int:
    """Drop `stale` fingerprints from the baseline file IN PLACE,
    preserving the surviving entries' justifications (write_baseline
    would reset them to TODO). Returns the number removed."""
    if not path or not os.path.exists(path) or not stale:
        return 0
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", [])
    dead = set(stale)
    kept = [e for e in entries if e.get("fingerprint") not in dead]
    removed = len(entries) - len(kept)
    if removed:
        data["entries"] = kept
        with open(path, "w") as fh:
            json.dump(data, fh, indent=2)
            fh.write("\n")
    return removed


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [
        {
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "justification": "TODO: justify or fix",
        }
        for f, fp in zip(findings, _suffixed_fingerprints(findings))
    ]
    with open(path, "w") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2)
        fh.write("\n")


# ------------------------------------------------------------------ #
# runner

def iter_python_files(paths: Iterable[str]) -> Iterator[Tuple[str, str]]:
    """Yield (abs_path, rel_path) for every lintable .py under `paths`."""
    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            # keep directory components: path-based allowlists (EDL301's
            # proto/service.py) and baseline fingerprints must match the
            # directory-walk spelling; fall back to the absolute path for
            # files outside the working tree
            rel = os.path.relpath(root, os.getcwd())
            yield root, (root if rel.startswith("..") else rel)
            continue
        base = os.path.dirname(root)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d not in ("__pycache__", ".git")
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py") or fn in EXCLUDED_FILES:
                    continue
                abs_path = os.path.join(dirpath, fn)
                yield abs_path, os.path.relpath(abs_path, base)


@dataclass
class AnalysisResult:
    findings: List[Finding]          # all unsuppressed findings
    new: List[Finding]               # not covered by the baseline
    baselined: List[Finding]         # covered by the baseline
    stale_baseline: List[str]        # baseline fingerprints no longer seen
    errors: List[str]                # unparseable files

    @property
    def ok(self) -> bool:
        # stale baseline entries FAIL the run (not a note): tolerated
        # debt that got fixed must leave the ledger (--prune-baseline),
        # or the baseline silently rots into covering future findings
        return not self.new and not self.errors and not self.stale_baseline


def run_analysis(
    paths: Sequence[str],
    rules: Optional[Sequence[Rule]] = None,
    baseline: Optional[Dict[str, str]] = None,
    select: Optional[Set[str]] = None,
) -> AnalysisResult:
    rules = select_rules(
        list(rules) if rules is not None else all_rules(), select
    )
    baseline = baseline or {}
    findings: List[Finding] = []
    errors: List[str] = []
    contexts: List[ModuleContext] = []
    for abs_path, rel_path in iter_python_files(paths):
        try:
            with open(abs_path, encoding="utf-8") as f:
                source = f.read()
            contexts.append(ModuleContext(abs_path, source, rel_path))
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel_path}: {e}")
    module_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]
    for ctx in contexts:
        for rule in module_rules:
            for finding in rule.check(ctx):
                if not ctx.suppressed(finding):
                    findings.append(finding)
    if project_rules:
        project = ProjectContext(contexts)
        for rule in project_rules:
            for finding in rule.check_project(project):
                if not project.suppressed(finding):
                    findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    fingerprints = _suffixed_fingerprints(findings)

    new, baselined = [], []
    for f, fp in zip(findings, fingerprints):
        (baselined if fp in baseline else new).append(f)
    live = set(fingerprints)
    stale = [fp for fp in baseline if fp not in live]
    return AnalysisResult(
        findings=findings, new=new, baselined=baselined,
        stale_baseline=stale, errors=errors,
    )
