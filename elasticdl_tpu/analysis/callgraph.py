"""Whole-program call graph for the project-wide (ProjectRule) passes.

Parses nothing itself — it is built from the ModuleContexts the runner
already parsed — and resolves calls with the cheapest analysis that is
right for THIS codebase (stdlib `ast` only, no type checker):

- ``self.m(...)``          -> the defining class or its project bases (MRO)
- ``ClassName(...)``       -> ``ClassName.__init__``
- ``ClassName.m(...)``     -> the unbound method
- ``self._attr.m(...)``    -> via the attr's inferred type(s); attrs are
  typed from constructor calls (``self._x = Foo(...)``), annotations
  (``self._x: Foo``, class-level ``_x: "Optional[Foo]" = None`` — string
  annotations are parsed, so forward references work), and annotated
  ``__init__`` parameters assigned to attrs (``self._x = journal`` where
  ``journal: ControlPlaneJournal``). Lookup walks the MRO, so a mixin's
  class-level annotation types the subclass's attribute too.
- ``local.m(...)``         -> via per-function local inference (a local
  assigned from a project-class constructor or an annotated parameter)
- ``mod.f(...)`` / ``f(...)`` -> module functions through the import map
- duck fallback: a method name defined by exactly ONE project class (and
  not shadowing a builtin-container/threading/file method) resolves to
  that class even when the receiver's type is unknown. This is what makes
  un-annotated glue code analyzable; the blocklist keeps ``d.get(...)``
  from resolving to ``TaskDispatcher.get``.

Deliberately NOT handled (callers must tolerate unresolved calls):
callbacks invoked through containers (``for cb in self._cbs: cb()``),
``getattr`` dispatch, and decorators that replace the function. A call
site that resolves to nothing contributes nothing — rules built on the
graph stay sound for what the graph DOES claim, and the runtime
lock-order recorder covers the dynamic remainder.
"""

from __future__ import annotations

import ast
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from elasticdl_tpu.analysis.core import ModuleContext

#: method names too generic for duck resolution — defined by builtin
#: containers / sync primitives / file objects, so an unknown receiver
#: is far more likely one of those than a project class
_COMMON_METHOD_NAMES: Set[str] = set()
for _t in (dict, list, set, frozenset, str, bytes, tuple):
    _COMMON_METHOD_NAMES.update(
        n for n in dir(_t) if not n.startswith("__")
    )
_COMMON_METHOD_NAMES |= {
    n for n in dir(threading.Lock()) if not n.startswith("__")
}
_COMMON_METHOD_NAMES |= {
    "acquire", "release", "wait", "notify", "notify_all", "start", "run",
    "join", "close", "open", "flush", "read", "write", "readline",
    "send", "recv", "submit", "result", "cancel", "is_set", "set",
    "clear", "get", "put", "inc", "dec", "observe", "info", "debug",
    "warning", "error", "exception", "critical", "log", "emit", "next",
    "stop", "reset", "name", "empty", "full", "fileno", "register",
}


@dataclass
class FunctionInfo:
    """One def: a method (class_name set) or a module-level function."""

    key: str                      # "rel/path.py::Class.method" | "::func"
    name: str
    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    module: ModuleContext
    class_name: Optional[str] = None

    @property
    def display(self) -> str:
        return f"{self.class_name}.{self.name}" if self.class_name else self.name


@dataclass
class ClassInfo:
    key: str                      # "rel/path.py::Class"
    name: str
    node: ast.ClassDef
    module: ModuleContext
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    #: attr -> set of project-class NAMES the attr may hold
    attr_types: Dict[str, Set[str]] = field(default_factory=dict)
    #: attr -> "lock" | "rlock" | "condition" (threading constructions
    #: seen anywhere in the class body)
    lock_attrs: Dict[str, str] = field(default_factory=dict)


def _func_defs(cls: ast.ClassDef) -> Iterator[ast.AST]:
    for child in cls.body:
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield child


_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}


def _lock_kind(value: ast.AST) -> Optional[str]:
    """'lock'/'rlock'/'condition' if value is a threading.X() construction
    (bare `Lock()` from-imports count too)."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if isinstance(f, ast.Attribute):
        return _LOCK_CTORS.get(f.attr)
    if isinstance(f, ast.Name):
        return _LOCK_CTORS.get(f.id)
    return None


class CallGraph:
    """Classes, functions, and a resolver — see the module docstring."""

    def __init__(self, modules: Sequence[ModuleContext]):
        self.modules = list(modules)
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.by_class_name: Dict[str, List[ClassInfo]] = {}
        #: method name -> [(ClassInfo, FunctionInfo)] across the project
        self._methods_by_name: Dict[str, List[Tuple[ClassInfo, FunctionInfo]]] = {}
        #: per module: local name -> imported module dotted path ("time")
        self._module_imports: Dict[str, Dict[str, str]] = {}
        #: per module: local name imported FROM somewhere ("CommitGate")
        self._from_imports: Dict[str, Set[str]] = {}
        self._mro_cache: Dict[str, List[ClassInfo]] = {}
        self._attr_cache: Dict[Tuple[str, str], Set[str]] = {}
        for m in self.modules:
            self._index_module(m)
        for cls in self.classes.values():
            self._infer_class_attrs(cls)

    # -------------------------------------------------------------- #
    # indexing

    def _index_module(self, ctx: ModuleContext) -> None:
        imports: Dict[str, str] = {}
        froms: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imports[(a.asname or a.name).split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    froms.add(a.asname or a.name)
        self._module_imports[ctx.rel_path] = imports
        self._from_imports[ctx.rel_path] = froms

        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                self._index_class(ctx, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{ctx.rel_path}::{node.name}"
                self.functions[key] = FunctionInfo(
                    key=key, name=node.name, node=node, module=ctx,
                )

    def _index_class(self, ctx: ModuleContext, node: ast.ClassDef) -> None:
        info = ClassInfo(
            key=f"{ctx.rel_path}::{node.name}", name=node.name,
            node=node, module=ctx,
        )
        for b in node.bases:
            if isinstance(b, ast.Name):
                info.bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                info.bases.append(b.attr)
        for fn in _func_defs(node):
            key = f"{ctx.rel_path}::{node.name}.{fn.name}"
            fi = FunctionInfo(
                key=key, name=fn.name, node=fn, module=ctx,
                class_name=node.name,
            )
            info.methods[fn.name] = fi
            self.functions[key] = fi
            self._methods_by_name.setdefault(fn.name, []).append((info, fi))
        self.classes[info.key] = info
        self.by_class_name.setdefault(info.name, []).append(info)

    # -------------------------------------------------------------- #
    # attribute / annotation type inference

    def _class_names_in_annotation(self, ann: ast.AST) -> Set[str]:
        """Project-class names mentioned anywhere in an annotation
        (handles Optional[X], X | None, and "quoted forward refs")."""
        out: Set[str] = set()
        stack = [ann]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                try:
                    stack.append(ast.parse(n.value, mode="eval").body)
                except SyntaxError:
                    continue
                continue
            for sub in ast.walk(n):
                if isinstance(sub, ast.Name) and sub.id in self.by_class_name:
                    out.add(sub.id)
                elif isinstance(sub, ast.Attribute) and sub.attr in self.by_class_name:
                    out.add(sub.attr)
        return out

    def _callee_class_name(self, value: ast.AST) -> Optional[str]:
        """Class name if value is `ClassName(...)` / `mod.ClassName(...)`."""
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None
        )
        return name if name in self.by_class_name else None

    def _infer_class_attrs(self, cls: ClassInfo) -> None:
        # class-level annotated declarations (mixin idiom:
        # `_journal: "Optional[ControlPlaneJournal]" = None`)
        for stmt in cls.node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                names = self._class_names_in_annotation(stmt.annotation)
                if names:
                    cls.attr_types.setdefault(stmt.target.id, set()).update(names)

        for fn in _func_defs(cls.node):
            params: Dict[str, Set[str]] = {}
            args = fn.args
            for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
                if a.annotation is not None:
                    names = self._class_names_in_annotation(a.annotation)
                    if names:
                        params[a.arg] = names
            for node in ast.walk(fn):
                target = value = ann = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value, ann = node.target, node.value, node.annotation
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    continue
                attr = target.attr
                kind = _lock_kind(value) if value is not None else None
                if kind is not None:
                    cls.lock_attrs.setdefault(attr, kind)
                    continue
                types: Set[str] = set()
                if ann is not None:
                    types |= self._class_names_in_annotation(ann)
                ctor = self._callee_class_name(value) if value is not None else None
                if ctor:
                    types.add(ctor)
                if isinstance(value, ast.Name) and value.id in params:
                    types |= params[value.id]
                if types:
                    cls.attr_types.setdefault(attr, set()).update(types)

    # -------------------------------------------------------------- #
    # resolution helpers

    def resolve_class_name(
        self, name: str, ctx: Optional[ModuleContext] = None
    ) -> List[ClassInfo]:
        """Candidates for a bare class name, preferring the referencing
        module's own class, then an explicit from-import, then any."""
        candidates = self.by_class_name.get(name, [])
        if len(candidates) <= 1 or ctx is None:
            return list(candidates)
        own = [c for c in candidates if c.module.rel_path == ctx.rel_path]
        if own:
            return own
        if name in self._from_imports.get(ctx.rel_path, set()):
            return list(candidates)
        return list(candidates)

    def mro(self, cls: ClassInfo) -> List[ClassInfo]:
        """The class plus its project bases, breadth-first (close enough
        to real MRO for method lookup in this codebase)."""
        cached = self._mro_cache.get(cls.key)
        if cached is not None:
            return cached
        out, queue, seen = [], [cls], set()
        while queue:
            c = queue.pop(0)
            if c.key in seen:
                continue
            seen.add(c.key)
            out.append(c)
            for base in c.bases:
                queue.extend(self.resolve_class_name(base, c.module))
        self._mro_cache[cls.key] = out
        return out

    def lookup_method(self, cls: ClassInfo, name: str) -> Optional[FunctionInfo]:
        for c in self.mro(cls):
            if name in c.methods:
                return c.methods[name]
        return None

    def attr_types_of(self, cls: ClassInfo, attr: str) -> Set[str]:
        """Inferred type names for self.<attr>, unioned over the MRO."""
        ck = (cls.key, attr)
        cached = self._attr_cache.get(ck)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for c in self.mro(cls):
            out |= c.attr_types.get(attr, set())
        self._attr_cache[ck] = out
        return out

    def lock_attrs_of(self, cls: ClassInfo) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for c in reversed(self.mro(cls)):
            out.update(c.lock_attrs)
        return out

    def _module_function(
        self, ctx: ModuleContext, name: str
    ) -> Optional[FunctionInfo]:
        return self.functions.get(f"{ctx.rel_path}::{name}")

    def local_types(self, fn: ast.AST) -> Dict[str, Set[str]]:
        """Per-function poor-man's locals typing: `x = ClassName(...)`
        assignments and annotated parameters."""
        out: Dict[str, Set[str]] = {}
        args = getattr(fn, "args", None)
        if args is not None:
            for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
                if a.annotation is not None:
                    names = self._class_names_in_annotation(a.annotation)
                    if names:
                        out[a.arg] = names
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                ctor = self._callee_class_name(node.value)
                if ctor:
                    out.setdefault(node.targets[0].id, set()).add(ctor)
        return out

    # -------------------------------------------------------------- #
    # call resolution

    def resolve_call(
        self,
        call: ast.Call,
        scope: FunctionInfo,
        local_types: Optional[Dict[str, Set[str]]] = None,
    ) -> List[FunctionInfo]:
        """Possible project callees of `call` made from inside `scope`.
        Empty list = unresolved (external, dynamic, or builtin)."""
        f = call.func
        ctx = scope.module
        if isinstance(f, ast.Name):
            return self._resolve_name_call(f.id, ctx)
        if not isinstance(f, ast.Attribute):
            return []
        method = f.attr
        recv = f.value

        # self.m(...) — exact MRO lookup on the enclosing class
        if isinstance(recv, ast.Name) and recv.id == "self" and scope.class_name:
            for cls in self.resolve_class_name(scope.class_name, ctx):
                m = self.lookup_method(cls, method)
                if m is not None:
                    return [m]
            return []

        # receivers whose class set we can infer
        type_names: Set[str] = set()
        if isinstance(recv, ast.Name):
            if recv.id in self.by_class_name:
                # ClassName.m(...) unbound
                type_names.add(recv.id)
            elif recv.id in self._module_imports.get(ctx.rel_path, {}):
                # mod.f(...): only ever a module function of a PROJECT
                # module; externals resolve to nothing (never duck-typed)
                dotted = self._module_imports[ctx.rel_path][recv.id]
                target = self._module_by_dotted(dotted)
                if target is not None:
                    fn = self._module_function(target, method)
                    if fn is not None:
                        return [fn]
                    for cls in self.by_class_name.get(method, []):
                        if cls.module.rel_path == target.rel_path:
                            init = self.lookup_method(cls, "__init__")
                            return [init] if init else []
                return []
            elif local_types and recv.id in local_types:
                type_names |= local_types[recv.id]
        elif (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and scope.class_name
        ):
            for cls in self.resolve_class_name(scope.class_name, ctx):
                type_names |= self.attr_types_of(cls, recv.attr)

        out: List[FunctionInfo] = []
        for tname in sorted(type_names):
            for cls in self.resolve_class_name(tname, ctx):
                m = self.lookup_method(cls, method)
                if m is not None and m not in out:
                    out.append(m)
        if out:
            return out

        # duck fallback: unique project definition, non-generic name
        if method not in _COMMON_METHOD_NAMES:
            owners = self._methods_by_name.get(method, [])
            if len(owners) == 1:
                return [owners[0][1]]
        return []

    def _resolve_name_call(
        self, name: str, ctx: ModuleContext
    ) -> List[FunctionInfo]:
        fn = self._module_function(ctx, name)
        if fn is not None:
            return [fn]
        for cls in self.resolve_class_name(name, ctx):
            init = self.lookup_method(cls, "__init__")
            if init is not None:
                return [init]
        return []

    def _module_by_dotted(self, dotted: str) -> Optional[ModuleContext]:
        """'elasticdl_tpu.master.journal' -> its ModuleContext (matched on
        the rel-path tail so partial trees still resolve)."""
        tail = dotted.replace(".", "/") + ".py"
        for m in self.modules:
            if m.rel_path == tail or m.rel_path.endswith("/" + tail):
                return m
        return None
