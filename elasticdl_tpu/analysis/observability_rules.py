"""EDL4xx: observability hygiene.

EDL401 metric-name-pattern
    A metric registered through the registry factories (`counter`,
    `gauge`, `histogram`) with a literal name that does not match the
    project naming pattern `edl_<subsystem>_<name>` (lowercase,
    underscore-separated — observability/registry._NAME_RE). The runtime
    registry rejects bad names too; this rule catches them at lint time,
    before the first scrape, and covers names the runtime path may not
    reach in tests (conditionally-registered metrics).

    Only literal string names are checkable statically; dynamic names are
    the runtime validator's job.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from elasticdl_tpu.analysis.core import Finding, ModuleContext, Rule, register

#: kept textually in sync with observability/registry._NAME_RE (a test
#: pins the two together)
METRIC_NAME_RE = re.compile(r"^edl_[a-z][a-z0-9]*_[a-z0-9_]*[a-z0-9]$")

_FACTORIES = {"counter", "gauge", "histogram"}


def _metric_name_arg(node: ast.Call) -> "ast.Constant | None":
    """The literal name argument of a registry-factory call, if any."""
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value
    return None


@register
class MetricNamePatternRule(Rule):
    id = "EDL401"
    name = "metric-name-pattern"
    doc = (
        "metric name outside the registry naming pattern "
        "edl_<subsystem>_<name> — keep the scrape surface grep-able"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            if attr not in _FACTORIES:
                continue
            name_node = _metric_name_arg(node)
            if name_node is None:
                continue
            value = name_node.value
            # only metric-shaped literals are in scope: a bare
            # `counter("x")` from an unrelated library (collections-style
            # counters take iterables, not names) would otherwise flag —
            # the registry convention is that every metric name starts
            # with edl_, so anything else passed to these factories is
            # either a naming violation (starts wrong) or not a metric at
            # all; the distinguishing signal is an identifier-looking
            # string
            if not re.match(r"^[A-Za-z_][A-Za-z0-9_]*$", value):
                continue
            if not METRIC_NAME_RE.match(value):
                yield self.finding(
                    ctx, name_node,
                    f"metric name {value!r} does not match "
                    "edl_<subsystem>_<name> (EDL401; see "
                    "docs/observability.md)",
                )
