"""EDL4xx: observability hygiene.

EDL401 metric-name-pattern
    A metric registered through the registry factories (`counter`,
    `gauge`, `histogram`) with a literal name that does not match the
    project naming pattern `edl_<subsystem>_<name>` (lowercase,
    underscore-separated — observability/registry._NAME_RE). The runtime
    registry rejects bad names too; this rule catches them at lint time,
    before the first scrape, and covers names the runtime path may not
    reach in tests (conditionally-registered metrics).

    Only literal string names are checkable statically; dynamic names are
    the runtime validator's job.

EDL402 span-emit-under-lock
    A span opened or an event emitted (`tracing.span`/`tracing.event`,
    `get_tracer().span/event`, or a directly-imported `span`/`event`)
    lexically inside the critical section of a `guarded_by:`-annotated
    lock — via `with self.<lock>:` or inside a method declared to hold it
    (`# holds: <lock>` / `_locked` suffix). Trace emission writes (and
    flushes) trace.jsonl under the tracer's own lock; doing that while
    holding a control-plane lock puts file I/O inside a contended critical
    section and couples the subsystem's lock to the tracer's. PR 4 fixed
    exactly this by hand in the process manager (the reform.spawn span now
    wraps the lock, not the reverse) and in the dispatcher (lease/report
    events emit after release); this rule codifies the idiom. Metric
    mutations (`.inc()`/`.set()`/`.observe()`) stay fine under locks —
    metric locks are leaf locks and touch no files.

    Emit after releasing: compute inside the lock, emit outside (the
    membership/dispatcher pattern), or open the span around the `with
    self._lock:` block (the process-manager pattern).

EDL404 span-sink-in-hot-loop
    A span opened or an event emitted (same call shapes as EDL402)
    lexically inside a PER-STEP hot loop — a for/while whose body
    dispatches device steps (`train_step`/`train_many`/`eval_step`/...,
    the EDL201 hot-loop definition). Every span/event emission writes
    (and flushes) trace.jsonl under the tracer lock: per-step emission
    puts file I/O on the training hot path, thousands of times per task.
    Per-step telemetry belongs in the structures built for it — the step
    profiler's phase accumulators (observability/profile.py: perf_counter
    reads + float adds) and the flight recorder's in-memory ring
    (observability/flight.py), which capture full fidelity without
    touching a file until an incident dumps them. Emit spans at task /
    rescale / reform granularity instead.

EDL405 unbounded-metric-label-cardinality
    A metric mutation (`.inc()`/`.set()`/`.observe()`/`.add()` on a
    registry metric) whose label VALUE derives from a loop variable —
    a `for` target or comprehension target lexically enclosing the
    call. Label values become registry dictionary keys that live
    forever: a label fed from a per-id / per-task / per-row loop grows
    the registry (and every scrape) without bound — the classic
    cardinality explosion. Bounded enumerations are fine and common:
    a loop over a module-level constant tuple (the profiler's PHASES)
    is recognized and exempt; a loop whose bound the linter cannot see
    (range(num_shards), dict iteration) but a reviewer CAN — per-shard
    labels bounded by --embedding_shards — carries an explicit
    `# edl-lint: disable=EDL405` with justification. Everything else
    should label by a bounded dimension (op, phase, method) and carry
    the unbounded one as a value, not a label.

EDL406 wall-clock-duration-measurement
    A subtraction whose BOTH operands are wall-clock stamps — a
    ``time.time()`` call and/or a local name assigned directly from one
    in the same scope (``t0 = time.time() ... time.time() - t0``). A
    wall-clock delta used as a duration is corrupted by NTP steps and
    leap adjustments: a 30 s clock slew lands as a 30 s "step time" in a
    histogram, a negative phase in the goodput ledger, a phantom reform
    spike — monotonic/perf_counter deltas are immune and cost the same.
    Epoch arithmetic against STORED wall-clock stamps (heartbeat
    staleness windows, cross-process `updated_at` comparisons) is
    intentionally out of scope: only local-local / call-local pairs
    flag, and the rare intended case carries a reviewed
    ``# edl-lint: disable=EDL406`` with justification.

EDL407 per-call-span-in-data-plane-hot-path
    A span opened or an event emitted (same call shapes as EDL402/404)
    inside a per-call function of the embedding data plane's fused
    pull/push hot path — the modules behind `pull_unique_multi`
    (embedding/data_plane.py, tier.py, shm.py, transport.py), in
    functions on the per-call path (pull*/push*/serve*/hedge*/retry*/
    the wire-call shims and codec helpers). These paths run per fused
    read — thousands of times per step at wire speed — and every raw
    span/event emission writes (and flushes) trace.jsonl under the
    tracer lock. Per-call telemetry on the data plane goes through the
    request-diary recorder (observability/reqtrace.py): `stage()` /
    `event()` land in the caller's open diaries cheaply when diaries
    are active and no-op otherwise, and tail-based sampling decides
    AFTER the call whether anything is worth keeping. Spans stay at
    phase/reshard granularity. Same emit detection as EDL404; distinct
    rule because the data plane's hot path is per-CALL (no train_step
    dispatch in sight for the hot-loop heuristic to catch).

EDL403 fsync-under-lock
    An ``os.fsync`` call lexically inside a `guarded_by:`-annotated
    lock's critical section. An fsync is milliseconds on local disk and
    tens of milliseconds on NFS/GCS-FUSE; under a control-plane lock it
    serializes every mutator behind the disk and bounds master dispatch
    throughput to ~1/fsync-latency fleet-wide — the exact wall the
    journal's group-commit pipeline (master/journal.py) exists to remove.
    The idiom this codifies: mutators ENQUEUE onto the journal's commit
    queue under their lock and wait for durability after releasing; only
    the journal's committer (and reviewed leaf-I/O teardown paths, via
    explicit `# edl-lint: disable=EDL403` with justification) fsyncs
    while holding a lock.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Set

from elasticdl_tpu.analysis.core import Finding, ModuleContext, Rule, register
from elasticdl_tpu.analysis.jax_rules import (
    _DISPATCH_METHODS,
    _called_attr_names,
)
from elasticdl_tpu.analysis.locks import (
    _CONSTRUCTION_METHODS,
    guarded_attrs,
    method_held_locks,
)

#: kept textually in sync with observability/registry._NAME_RE (a test
#: pins the two together)
METRIC_NAME_RE = re.compile(r"^edl_[a-z][a-z0-9]*_[a-z0-9_]*[a-z0-9]$")

_FACTORIES = {"counter", "gauge", "histogram"}


def _metric_name_arg(node: ast.Call) -> "ast.Constant | None":
    """The literal name argument of a registry-factory call, if any."""
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value
    return None


@register
class MetricNamePatternRule(Rule):
    id = "EDL401"
    name = "metric-name-pattern"
    doc = (
        "metric name outside the registry naming pattern "
        "edl_<subsystem>_<name> — keep the scrape surface grep-able"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            if attr not in _FACTORIES:
                continue
            name_node = _metric_name_arg(node)
            if name_node is None:
                continue
            value = name_node.value
            # only metric-shaped literals are in scope: a bare
            # `counter("x")` from an unrelated library (collections-style
            # counters take iterables, not names) would otherwise flag —
            # the registry convention is that every metric name starts
            # with edl_, so anything else passed to these factories is
            # either a naming violation (starts wrong) or not a metric at
            # all; the distinguishing signal is an identifier-looking
            # string
            if not re.match(r"^[A-Za-z_][A-Za-z0-9_]*$", value):
                continue
            if not METRIC_NAME_RE.match(value):
                yield self.finding(
                    ctx, name_node,
                    f"metric name {value!r} does not match "
                    "edl_<subsystem>_<name> (EDL401; see "
                    "docs/observability.md)",
                )


# ------------------------------------------------------------------ #
# EDL402 span-emit-under-lock


_EMIT_ATTRS = {"span", "event"}


def _direct_emit_imports(tree: ast.AST) -> Set[str]:
    """Local names bound to tracing.span/tracing.event by a
    `from ...observability.tracing import span, event` (any alias)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("tracing"):
            for alias in node.names:
                if alias.name in _EMIT_ATTRS:
                    names.add(alias.asname or alias.name)
    return names


def _is_emit_call(node: ast.Call, direct_names: Set[str]) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in direct_names
    if not isinstance(func, ast.Attribute) or func.attr not in _EMIT_ATTRS:
        return False
    base = func.value
    # tracing.span(...) / tracing.event(...) — the tree's idiom (lazy
    # in-function imports make import-tracking unreliable, so the base
    # NAME is the signal)
    if isinstance(base, ast.Name) and base.id == "tracing":
        return True
    # get_tracer().span(...) / tracing.get_tracer().event(...)
    if isinstance(base, ast.Call):
        f = base.func
        fname = (
            f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else ""
        )
        return fname == "get_tracer"
    return False


class _CallUnderLockVisitor(ast.NodeVisitor):
    """Walk one method body tracking which class locks are lexically held
    (same `with self.<lock>` semantics as EDL101's visitor), flagging
    calls matching `predicate` while any of them is. Shared by EDL402
    (span/event emission) and EDL403 (os.fsync)."""

    def __init__(self, rule: Rule, ctx: ModuleContext,
                 class_locks: Set[str], held: Set[str],
                 predicate, message_fn):
        self.rule = rule
        self.ctx = ctx
        self.class_locks = class_locks
        self.held = set(held)
        self.predicate = predicate
        self.message_fn = message_fn
        self.findings: List[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        # items are processed IN ORDER, growing the held set as each lock
        # is acquired: `with tracing.span(...): with self._lock:` (the
        # span wrapping the lock) is the idiomatic GOOD shape, while the
        # combined `with self._lock, tracing.span(...):` acquires the
        # lock FIRST and then opens the span under it — flagged
        saved = set(self.held)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in self.class_locks
            ):
                self.held.add(expr.attr)
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    def _visit_deferred(self, node: ast.AST) -> None:
        # nested defs/lambdas run later, on whatever thread calls them
        saved = set(self.held)
        self.held = set()
        self.generic_visit(node)
        self.held = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_deferred(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_deferred(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_deferred(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.held and self.predicate(node):
            locks = ", ".join(sorted(self.held))
            self.findings.append(
                self.rule.finding(
                    ctx=self.ctx, node=node,
                    message=self.message_fn(node, locks),
                )
            )
        self.generic_visit(node)


@register
class SpanEmitUnderLockRule(Rule):
    id = "EDL402"
    name = "span-emit-under-lock"
    doc = (
        "span/event emitted inside a guarded_by-annotated lock's critical "
        "section — trace emission does file I/O; emit after releasing"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        direct_names = _direct_emit_imports(ctx.tree)

        def message(node: ast.Call, locks: str) -> str:
            kind = (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else node.func.id
            )
            return (
                f"{kind} emission inside the critical section of "
                f"self.{locks} — trace emission is file I/O under "
                "the tracer lock; emit after releasing, or open "
                "the span around the lock (EDL402)"
            )

        yield from _scan_calls_under_locks(
            self, ctx, lambda node: _is_emit_call(node, direct_names),
            message,
        )


def _scan_calls_under_locks(rule, ctx, predicate, message_fn):
    """Run the held-lock call scan over every guarded class (the shared
    chassis of EDL402/EDL403)."""
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = guarded_attrs(ctx, cls)
        if not guarded:
            continue
        class_locks = set(guarded.values())
        for node in cls.body:
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if node.name in _CONSTRUCTION_METHODS:
                # construction happens-before publication: the lock
                # cannot be contended yet (EDL101's exemption)
                continue
            held = method_held_locks(ctx, node, class_locks) & class_locks
            visitor = _CallUnderLockVisitor(
                rule, ctx, class_locks, held, predicate, message_fn
            )
            for stmt in node.body:
                visitor.visit(stmt)
            yield from visitor.findings


# ------------------------------------------------------------------ #
# EDL403 fsync-under-lock


def _direct_fsync_imports(tree: ast.AST) -> Set[str]:
    """Local names bound to os.fsync by `from os import fsync` (aliases)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for alias in node.names:
                if alias.name == "fsync":
                    names.add(alias.asname or alias.name)
    return names


def _is_fsync_call(node: ast.Call, direct_names: Set[str]) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in direct_names
    return (
        isinstance(func, ast.Attribute)
        and func.attr == "fsync"
        and isinstance(func.value, ast.Name)
        and func.value.id == "os"
    )


@register
class FsyncUnderLockRule(Rule):
    id = "EDL403"
    name = "fsync-under-lock"
    doc = (
        "os.fsync inside a guarded_by-annotated lock's critical section — "
        "per-commit fsync under a control-plane lock serializes every "
        "mutator behind the disk; enqueue on the journal's group-commit "
        "queue and wait after releasing instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        direct_names = _direct_fsync_imports(ctx.tree)

        def message(node: ast.Call, locks: str) -> str:
            return (
                f"os.fsync inside the critical section of self.{locks} — "
                "this bounds fleet-wide throughput to ~1/fsync-latency; "
                "route the record through the journal's group-commit "
                "queue and wait for durability AFTER releasing the lock "
                "(EDL403; the journal committer and reviewed leaf-I/O "
                "teardowns carry explicit disables)"
            )

        yield from _scan_calls_under_locks(
            self, ctx, lambda node: _is_fsync_call(node, direct_names),
            message,
        )


# ------------------------------------------------------------------ #
# EDL404 span-sink-in-hot-loop


@register
class SpanSinkInHotLoopRule(Rule):
    id = "EDL404"
    name = "span-sink-in-hot-loop"
    doc = (
        "span/event emitted inside a per-step hot loop — trace emission "
        "is file I/O; per-step telemetry goes through the flight ring / "
        "step profiler, spans stay at task/rescale granularity"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        direct_names = _direct_emit_imports(ctx.tree)
        reported: Set[int] = set()   # a call nested in two loops fires once
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = list(node.body) + list(node.orelse)
            called: Set[str] = set()
            for stmt in body:
                called |= _called_attr_names(stmt)
            if not (called & _DISPATCH_METHODS):
                continue
            for stmt in body:
                for sub in ast.walk(stmt):
                    if (
                        isinstance(sub, ast.Call)
                        and _is_emit_call(sub, direct_names)
                        and id(sub) not in reported
                    ):
                        reported.add(id(sub))
                        kind = (
                            sub.func.attr
                            if isinstance(sub.func, ast.Attribute)
                            else sub.func.id
                        )
                        yield self.finding(
                            ctx, sub,
                            f"{kind} emission inside a per-step hot loop "
                            "— trace emission writes trace.jsonl; "
                            "per-step telemetry goes through the flight "
                            "ring / step profiler "
                            "(observability/flight.py, profile.py), "
                            "spans stay at task/rescale granularity "
                            "(EDL404)",
                        )


# ------------------------------------------------------------------ #
# EDL407 per-call-span-in-data-plane-hot-path


#: the fused pull/push data plane — every module a `pull_unique_multi`
#: traverses between the tier and the owner's store
_DATA_PLANE_HOT_MODULES = (
    "elasticdl_tpu/embedding/data_plane.py",
    "elasticdl_tpu/embedding/tier.py",
    "elasticdl_tpu/embedding/shm.py",
    "elasticdl_tpu/embedding/transport.py",
)

#: per-call function names inside those modules: the pull/push ladders,
#: the hedge race, retry rungs, wire-call shims (gRPC + shm ring), the
#: server-side serve path and the codec helpers. Case-insensitive so
#: the CamelCase gRPC servicer methods (EmbeddingPullMulti) match.
_HOT_FUNC_RE = re.compile(
    r"^_?(pull|push|serve|hedge|retry|call|shm|wire|codec|"
    r"encode|decode|embedding)",
    re.IGNORECASE,
)


def _in_data_plane_module(ctx: ModuleContext) -> bool:
    return any(ctx.rel_path.endswith(m) for m in _DATA_PLANE_HOT_MODULES)


@register
class PerCallSpanInDataPlaneHotPathRule(Rule):
    id = "EDL407"
    name = "per-call-span-in-data-plane-hot-path"
    doc = (
        "span/event emitted inside the fused pull/push data-plane hot "
        "path — per-call telemetry goes through the request-diary "
        "recorder (reqtrace.stage()/event(), tail-sampled); spans stay "
        "at phase/reshard granularity"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _in_data_plane_module(ctx):
            return
        direct_names = _direct_emit_imports(ctx.tree)
        reported: Set[int] = set()   # nested hot defs fire once
        for node in ast.walk(ctx.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if not _HOT_FUNC_RE.match(node.name):
                continue
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and _is_emit_call(sub, direct_names)
                    and id(sub) not in reported
                ):
                    reported.add(id(sub))
                    kind = (
                        sub.func.attr
                        if isinstance(sub.func, ast.Attribute)
                        else sub.func.id
                    )
                    yield self.finding(
                        ctx, sub,
                        f"{kind} emission inside the data plane's "
                        f"per-call hot path ({node.name}) — trace "
                        "emission writes trace.jsonl per fused call; "
                        "route per-call telemetry through the request-"
                        "diary recorder (observability/reqtrace.py: "
                        "stage()/event() land in the caller's diary, "
                        "tail-based sampling keeps only the slow/"
                        "errored/degraded ones), and keep spans at "
                        "phase/reshard granularity (EDL407)",
                    )


# ------------------------------------------------------------------ #
# EDL406 wall-clock-duration-measurement


def _direct_time_imports(tree: ast.AST) -> Set[str]:
    """Local names bound to time.time by `from time import time` (any
    alias)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    names.add(alias.asname or alias.name)
    return names


def _is_wallclock_call(node: ast.AST, direct_names: Set[str]) -> bool:
    if not isinstance(node, ast.Call) or node.args or node.keywords:
        return False
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in direct_names
    return (
        isinstance(func, ast.Attribute)
        and func.attr == "time"
        and isinstance(func.value, ast.Name)
        and func.value.id == "time"
    )


def _scope_bodies(tree: ast.AST):
    """One statement body per scope: the module body and every function
    body, each analyzed independently — a name tracked in one function
    says nothing about another's."""
    yield getattr(tree, "body", [])
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            yield node.body if not isinstance(node, ast.Lambda) \
                else [node.body]


def _walk_scope(body):
    """ast.walk over a scope body WITHOUT descending into nested
    function/lambda scopes (those get their own _scope_bodies entry)."""
    from collections import deque

    queue = deque(body)
    while queue:
        node = queue.popleft()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested scope: its body gets its own _scope_bodies entry
            continue
        queue.extend(ast.iter_child_nodes(node))


@register
class WallClockDurationRule(Rule):
    id = "EDL406"
    name = "wall-clock-duration-measurement"
    doc = (
        "time.time() delta used as a duration — NTP steps corrupt "
        "ledgers and histograms; use time.monotonic()/perf_counter() "
        "for durations (epoch arithmetic against stored stamps carries "
        "a reviewed disable)"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        direct_names = _direct_time_imports(ctx.tree)
        for body in _scope_bodies(ctx.tree):
            # pass 1: simple names assigned DIRECTLY from time.time() in
            # this scope (nested defs are separate scopes, not entered)
            tracked: Set[str] = set()
            for node in _walk_scope(body):
                if isinstance(node, ast.Assign) and _is_wallclock_call(
                    node.value, direct_names
                ):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            tracked.add(target.id)

            def stampish(node: ast.AST) -> bool:
                return _is_wallclock_call(node, direct_names) or (
                    isinstance(node, ast.Name) and node.id in tracked
                )

            for node in _walk_scope(body):
                if (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and stampish(node.left)
                    and stampish(node.right)
                ):
                    yield self.finding(
                        ctx, node,
                        "wall-clock delta used as a duration — an "
                        "NTP step lands here as a phantom (or "
                        "negative) interval; measure durations with "
                        "time.monotonic()/perf_counter() (EDL406; "
                        "intended epoch arithmetic carries a "
                        "reviewed disable)",
                    )


# ------------------------------------------------------------------ #
# EDL405 unbounded-metric-label-cardinality


#: metric mutator attribute names whose keyword args are label values
_MUTATOR_ATTRS = {"inc", "set", "observe", "add"}

#: keyword args of the mutators that are NOT labels
_NON_LABEL_KWARGS = {"n", "value"}


def _metric_var_names(tree: ast.AST) -> Set[str]:
    """Names bound (anywhere) to a registry-factory call result:
    `X = reg.counter(...)` / `X = registry.gauge(...)` — the receivers
    whose mutator keywords are label values."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        func = value.func
        attr = (
            func.attr if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name) else ""
        )
        if attr not in _FACTORIES:
            continue
        # only metric-shaped factory calls (same literal-name gate as
        # EDL401 — a collections.Counter(...) assignment stays out)
        name_node = _metric_name_arg(value)
        if name_node is None or not name_node.value.startswith("edl_"):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                names.add(target.id)
            elif isinstance(target, ast.Attribute):
                names.add(target.attr)
    return names


def _module_const_seqs(tree: ast.AST) -> Set[str]:
    """Module-level names bound to a literal tuple/list of constants —
    the recognizably-BOUNDED iterables (profile.py's PHASES)."""
    out: Set[str] = set()
    for node in getattr(tree, "body", []):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if isinstance(v, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) for e in v.elts
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out.add(target.id)
    return out


def _is_bounded_iter(node: ast.AST, const_seqs: Set[str]) -> bool:
    """Iterables whose cardinality is statically knowable: a literal
    tuple/list (of anything), or a module-level constant sequence by
    name. range()/data-driven iterables are NOT bounded as far as the
    linter can see — a reviewer may know better (disable with
    justification)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return True
    if isinstance(node, ast.Name) and node.id in const_seqs:
        return True
    return False


def _target_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(target):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
    return out


class _LabelCardinalityVisitor(ast.NodeVisitor):
    """Walk one scope tracking loop-bound names from UNBOUNDED iterables;
    flag metric-mutator calls whose label keyword values mention one."""

    def __init__(self, rule: Rule, ctx: ModuleContext,
                 metric_names: Set[str], const_seqs: Set[str]):
        self.rule = rule
        self.ctx = ctx
        self.metric_names = metric_names
        self.const_seqs = const_seqs
        self.loop_vars: Set[str] = set()
        self.findings: List[Finding] = []

    def visit_For(self, node: ast.For) -> None:
        added: Set[str] = set()
        if not _is_bounded_iter(node.iter, self.const_seqs):
            added = _target_names(node.target) - self.loop_vars
            self.loop_vars |= added
        self.generic_visit(node)
        self.loop_vars -= added

    def _visit_comp(self, node) -> None:
        added: Set[str] = set()
        for gen in node.generators:
            if not _is_bounded_iter(gen.iter, self.const_seqs):
                added |= _target_names(gen.target) - self.loop_vars
        self.loop_vars |= added
        self.generic_visit(node)
        self.loop_vars -= added

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_GeneratorExp = _visit_comp
    visit_DictComp = _visit_comp

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (
            self.loop_vars
            and isinstance(func, ast.Attribute)
            and func.attr in _MUTATOR_ATTRS
            and self._receiver_is_metric(func.value)
        ):
            for kw in node.keywords:
                if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                    continue
                used = {
                    n.id for n in ast.walk(kw.value)
                    if isinstance(n, ast.Name)
                } & self.loop_vars
                if used:
                    self.findings.append(self.rule.finding(
                        self.ctx, node,
                        f"label {kw.arg!r} derives from loop "
                        f"variable(s) {sorted(used)} — per-iteration "
                        "label values grow the registry without bound; "
                        "label by a bounded dimension instead, or "
                        "disable with the bound's justification "
                        "(EDL405)",
                    ))
                    break
        self.generic_visit(node)

    def _receiver_is_metric(self, base: ast.AST) -> bool:
        if isinstance(base, ast.Name):
            return base.id in self.metric_names
        if isinstance(base, ast.Attribute):
            return base.attr in self.metric_names
        return False


@register
class UnboundedMetricLabelCardinalityRule(Rule):
    id = "EDL405"
    name = "unbounded-metric-label-cardinality"
    doc = (
        "metric label value derived from a loop variable over an "
        "unbounded iterable — per-id/per-task labels explode the "
        "registry; label by bounded dimensions"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        metric_names = _metric_var_names(ctx.tree)
        if not metric_names:
            return
        const_seqs = _module_const_seqs(ctx.tree)
        visitor = _LabelCardinalityVisitor(
            self, ctx, metric_names, const_seqs)
        visitor.visit(ctx.tree)
        yield from visitor.findings
