"""`python -m elasticdl_tpu.analysis` — run edl-lint over the tree.

Exit codes: 0 clean (or every finding baselined), 1 new findings or
parse errors, 2 usage errors. The default target is the installed
`elasticdl_tpu` package directory; the default baseline is
`.edl-lint-baseline.json` next to `pyproject.toml` (repo checkouts) or
absent (installed wheels).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from elasticdl_tpu.analysis.core import (
    all_rules,
    load_baseline,
    run_analysis,
    write_baseline,
)

BASELINE_NAME = ".edl-lint-baseline.json"


def _default_paths() -> List[str]:
    import elasticdl_tpu

    return [os.path.dirname(os.path.abspath(elasticdl_tpu.__file__))]


def _default_baseline(paths: List[str]) -> Optional[str]:
    """Walk up from the first target looking for the checked-in baseline."""
    probe = os.path.abspath(paths[0])
    for _ in range(6):
        candidate = os.path.join(probe, BASELINE_NAME)
        if os.path.exists(candidate):
            return candidate
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.analysis",
        description="project-specific static analysis (edl-lint)",
    )
    parser.add_argument(
        "paths", nargs="*", help="files/directories to lint "
        "(default: the elasticdl_tpu package)",
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: nearest {BASELINE_NAME})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline: report every finding as new",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids/names to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name}: {rule.doc}")
        return 0

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or _default_baseline(paths)
    baseline = (
        {} if args.no_baseline or not baseline_path
        else load_baseline(baseline_path)
    )
    select = {s.strip() for s in args.select.split(",") if s.strip()} or None

    result = run_analysis(paths, baseline=baseline, select=select)

    if args.write_baseline:
        target = baseline_path or os.path.join(os.getcwd(), BASELINE_NAME)
        write_baseline(target, result.findings)
        print(f"wrote {len(result.findings)} entries to {target}")
        return 0

    if args.json:
        print(json.dumps(
            {
                "new": [f.__dict__ for f in result.new],
                "baselined": [f.__dict__ for f in result.baselined],
                "stale_baseline": result.stale_baseline,
                "errors": result.errors,
                "ok": result.ok,
            },
            indent=2,
        ))
    else:
        for f in result.new:
            print(f.render())
        for err in result.errors:
            print(f"parse error: {err}")
        if result.stale_baseline:
            print(
                f"note: {len(result.stale_baseline)} stale baseline "
                "entr(y/ies) — fixed findings; prune the baseline:"
            )
            for fp in result.stale_baseline:
                print(f"  {fp}")
        n_new, n_base = len(result.new), len(result.baselined)
        print(
            f"edl-lint: {n_new} new finding(s), {n_base} baselined, "
            f"{len(result.errors)} error(s)"
        )
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
