"""`python -m elasticdl_tpu.analysis` — run edl-lint over the tree.

Exit codes: 0 clean (or every finding baselined), 1 new findings, parse
errors, or STALE baseline entries (tolerated debt that got fixed must
leave the ledger — run `--prune-baseline`), 2 usage errors. The default
target is the installed `elasticdl_tpu` package directory; the default
baseline is `.edl-lint-baseline.json` next to `pyproject.toml` (repo
checkouts) or absent (installed wheels).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from elasticdl_tpu.analysis.core import (
    all_rules,
    load_baseline,
    prune_baseline,
    run_analysis,
    write_baseline,
)

BASELINE_NAME = ".edl-lint-baseline.json"


def _default_paths() -> List[str]:
    import elasticdl_tpu

    return [os.path.dirname(os.path.abspath(elasticdl_tpu.__file__))]


def _default_baseline(paths: List[str]) -> Optional[str]:
    """Walk up from the first target looking for the checked-in baseline."""
    probe = os.path.abspath(paths[0])
    for _ in range(6):
        candidate = os.path.join(probe, BASELINE_NAME)
        if os.path.exists(candidate):
            return candidate
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None


def _explain(rule_id: str) -> int:
    """`--explain EDL102`: the rule's FULL class docstring — the what,
    the why-it-matters-here, and the sanctioned fix patterns — not just
    the one-line `doc` the listing shows."""
    wanted = rule_id.strip().lower()
    for rule in all_rules():
        if wanted in (rule.id.lower(), rule.name.lower()):
            print(f"{rule.id} ({rule.name})")
            body = (type(rule).__doc__ or rule.doc or "").rstrip()
            import inspect

            print(inspect.cleandoc(body) if body else "(no documentation)")
            return 0
    print(f"error: no such rule: {rule_id}", file=sys.stderr)
    return 2


def _github_annotation(f) -> str:
    """One GitHub Actions workflow command per finding: the web UI pins
    the message to the file/line in the PR diff."""
    msg = f"{f.rule} ({f.name}) {f.message}"
    # workflow-command escaping: %, CR, LF in properties and message
    msg = (msg.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A"))
    return (
        f"::error file={f.path},line={f.line},col={f.col},"
        f"title=edl-lint {f.rule}::{msg}"
    )


def _emit_lock_graph(paths: List[str], dest: str) -> None:
    """Build the EDL102 static lock-acquisition graph over `paths` and
    write it to `dest` (.dot extension → DOT, else JSON)."""
    from elasticdl_tpu.analysis.concurrency import (
        build_lock_graph,
        render_lock_graph_dot,
    )
    from elasticdl_tpu.analysis.core import (
        ModuleContext,
        ProjectContext,
        iter_python_files,
    )

    contexts = []
    for abs_path, rel_path in iter_python_files(paths):
        try:
            with open(abs_path, encoding="utf-8") as fh:
                contexts.append(ModuleContext(abs_path, fh.read(), rel_path))
        except (SyntaxError, UnicodeDecodeError):
            continue
    graph = build_lock_graph(ProjectContext(contexts))
    with open(dest, "w", encoding="utf-8") as fh:
        if dest.endswith(".dot"):
            fh.write(render_lock_graph_dot(graph))
        else:
            json.dump(graph, fh, indent=2)
            fh.write("\n")
    print(
        f"lock graph: {len(graph['nodes'])} lock(s), "
        f"{len(graph['edges'])} edge(s), {len(graph['cycles'])} cycle(s) "
        f"-> {dest}"
    )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.analysis",
        description="project-specific static analysis (edl-lint)",
    )
    parser.add_argument(
        "paths", nargs="*", help="files/directories to lint "
        "(default: the elasticdl_tpu package)",
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    parser.add_argument(
        "--format", choices=("text", "github"), default="text",
        help="finding output format: 'github' emits workflow error "
        "annotations (::error file=...) for the CI job",
    )
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: nearest {BASELINE_NAME})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline: report every finding as new",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--prune-baseline", action="store_true",
        help="drop stale (fixed) entries from the baseline file, keeping "
        "surviving justifications, then report as usual",
    )
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids/names to run; family prefixes work "
        "(--select EDL1 runs every EDL1xx rule)",
    )
    parser.add_argument(
        "--explain", default=None, metavar="RULE",
        help="print a rule's full documentation (docstring) and exit",
    )
    parser.add_argument(
        "--lock-graph", default=None, metavar="DEST",
        help="also emit the EDL102 static lock-acquisition graph to DEST "
        "(.dot -> DOT, anything else -> JSON)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name}: {rule.doc}")
        return 0
    if args.explain:
        return _explain(args.explain)

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or _default_baseline(paths)
    baseline = (
        {} if args.no_baseline or not baseline_path
        else load_baseline(baseline_path)
    )
    select = {s.strip() for s in args.select.split(",") if s.strip()} or None

    result = run_analysis(paths, baseline=baseline, select=select)

    if args.lock_graph:
        _emit_lock_graph(paths, args.lock_graph)

    if args.write_baseline:
        target = baseline_path or os.path.join(os.getcwd(), BASELINE_NAME)
        write_baseline(target, result.findings)
        print(f"wrote {len(result.findings)} entries to {target}")
        return 0

    if args.prune_baseline and result.stale_baseline:
        if not baseline_path:
            print("error: --prune-baseline without a baseline file",
                  file=sys.stderr)
            return 2
        removed = prune_baseline(baseline_path, result.stale_baseline)
        print(f"pruned {removed} stale entr(y/ies) from {baseline_path}")
        result.stale_baseline = []

    if args.json:
        print(json.dumps(
            {
                "new": [f.__dict__ for f in result.new],
                "baselined": [f.__dict__ for f in result.baselined],
                "stale_baseline": result.stale_baseline,
                "errors": result.errors,
                "ok": result.ok,
            },
            indent=2,
        ))
    elif args.format == "github":
        for f in result.new:
            print(_github_annotation(f))
        for err in result.errors:
            print(f"::error title=edl-lint parse error::{err}")
        for fp in result.stale_baseline:
            print(f"::error title=edl-lint stale baseline::{fp} no longer "
                  "fires — run --prune-baseline")
        n_new, n_base = len(result.new), len(result.baselined)
        print(
            f"edl-lint: {n_new} new finding(s), {n_base} baselined, "
            f"{len(result.errors)} error(s)"
        )
    else:
        for f in result.new:
            print(f.render())
        for err in result.errors:
            print(f"parse error: {err}")
        if result.stale_baseline:
            print(
                f"STALE baseline: {len(result.stale_baseline)} entr(y/ies) "
                "no longer fire — fixed findings must leave the ledger "
                "(run --prune-baseline); failing"
            )
            for fp in result.stale_baseline:
                print(f"  {fp}")
        n_new, n_base = len(result.new), len(result.baselined)
        print(
            f"edl-lint: {n_new} new finding(s), {n_base} baselined, "
            f"{len(result.errors)} error(s)"
        )
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
