"""Runtime lock-order recording for the master control plane.

The static guarded-by pass proves accesses happen under *a* lock; it says
nothing about the order different threads take *several* locks in. The
master holds four independent locks (membership, dispatcher, process
manager, servicer) and three thread families (gRPC handler pool, watcher,
wait loop) — a new call path that nests two of them in opposite orders is
a deadlock that strikes only under load. This module makes the order
observable in tests:

    rec = LockOrderRecorder()
    rec.instrument(membership, name="membership")
    rec.instrument(dispatcher, name="dispatcher")
    ... drive the control plane ...
    rec.assert_no_cycles()

`instrument` replaces the object's `_lock` with a recording wrapper.
Every acquisition records edges {already-held lock} -> {acquired lock}
into one process-global-per-recorder directed graph; a cycle in that
graph is a lock-order inversion — a *potential* deadlock — even if the
run never actually deadlocked (the graph unions orders across threads,
which is exactly what wall-clock luck hides). With raise_on_cycle=True
(default) the offending acquire raises immediately, pointing at both
sites; the chaos smoke runs with it enabled so any inversion introduced
into the control plane fails tier-1 deterministically.

Re-entrant acquisition of the SAME recorded lock is reported as its own
violation. On a plain (non-reentrant) `threading.Lock` it ALWAYS raises —
even with raise_on_cycle=False — because proceeding would self-deadlock
the calling thread on the spot, hanging the test instead of failing it.
On an RLock (where proceeding is safe) it is recorded and raises only
under raise_on_cycle.
"""

from __future__ import annotations

import _thread
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

#: lock types where re-acquisition by the holder deadlocks immediately
_NON_REENTRANT_TYPES = (_thread.LockType,)


class LockOrderViolation(RuntimeError):
    """A lock acquisition created a cycle in the acquisition-order graph."""


def _acquisition_site() -> str:
    """most-recent caller outside this module, as 'file:line (func)'."""
    for frame in reversed(traceback.extract_stack()[:-2]):
        if "lockorder" not in frame.filename:
            return f"{frame.filename}:{frame.lineno} ({frame.name})"
    return "<unknown>"


class _RecordingLock:
    """Wraps a lock, reporting acquire/release to the recorder.

    Supports the contexts the control plane uses: `with lock:` and
    explicit acquire()/release(). Only successful acquisitions create
    edges (a failed non-blocking try-acquire records nothing); when a
    successful acquire closes a cycle under raise_on_cycle, the lock is
    released again before the violation propagates, so the failing test
    does not strand it for other threads."""

    def __init__(self, inner, name: str, recorder: "LockOrderRecorder"):
        self._inner = inner
        self._name = name
        self._recorder = recorder

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._recorder._before_acquire(
            self._name, isinstance(self._inner, _NON_REENTRANT_TYPES)
        )
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._recorder._acquired(self._name)
            except LockOrderViolation:
                self.release()   # inner lock AND held-stack entry
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        self._recorder._released(self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name: str):
        # Condition support: wait/wait_for/notify/notify_all pass through
        # to the wrapped primitive. wait() internally releases and
        # re-acquires the UNDERLYING lock without telling the recorder —
        # the held-stack deliberately keeps the lock "held" across the
        # wait, matching the lexical `with cv:` nesting the static
        # analyzer (EDL102) sees, so the two graphs stay comparable.
        return getattr(self._inner, name)


class LockOrderRecorder:
    def __init__(self, raise_on_cycle: bool = True):
        self.raise_on_cycle = raise_on_cycle
        # edge (held -> acquired) -> first acquisition site that created it
        self._edges: Dict[Tuple[str, str], str] = {}
        self._violations: List[str] = []
        self._meta = threading.Lock()
        self._held = threading.local()

    # -------------------------------------------------------------- #
    # instrumentation

    def wrap(self, lock, name: str) -> _RecordingLock:
        return _RecordingLock(lock, name, self)

    def instrument(self, obj, name: Optional[str] = None, attr: str = "_lock"):
        """Replace `obj.<attr>` with a recording wrapper (idempotent)."""
        lock = getattr(obj, attr)
        if isinstance(lock, _RecordingLock):
            return lock
        label = name if name is not None else f"{type(obj).__name__}{attr}"
        wrapped = self.wrap(lock, label)
        setattr(obj, attr, wrapped)
        return wrapped

    # -------------------------------------------------------------- #
    # recording

    def _held_stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def _before_acquire(self, name: str, non_reentrant: bool) -> None:
        held = self._held_stack()
        if name in held:
            site = _acquisition_site()
            msg = (
                f"re-entrant acquisition of lock '{name}' at {site} "
                "(already held by this thread)"
            )
            if non_reentrant:
                # proceeding would self-deadlock THIS thread right here:
                # raising is the only outcome that fails the test instead
                # of hanging it, so observe mode doesn't apply
                with self._meta:
                    self._violations.append(msg + " — self-deadlock on a "
                                            "non-reentrant lock")
                raise LockOrderViolation(msg)
            self._record_violation(msg)

    def _acquired(self, name: str) -> None:
        """Record edges for a SUCCESSFUL acquire (failed try-acquires
        create none). Raises (after the caller releases the inner lock)
        when the new edge closes a cycle under raise_on_cycle."""
        held = self._held_stack()
        if name in held:       # re-entrant on an RLock: no edge, no push
            held.append(name)
            return
        site = _acquisition_site()
        try:
            with self._meta:
                for h in held:
                    edge = (h, name)
                    if edge not in self._edges:
                        self._edges[edge] = site
                        cycle = self._find_cycle(name, h)
                        if cycle is not None:
                            self._record_violation(
                                self._cycle_message(cycle, site), locked=True
                            )
        finally:
            # push even when raising: acquire() releases the inner lock on
            # violation and _released pops this entry, keeping the stack
            # balanced either way
            held.append(name)

    def _released(self, name: str) -> None:
        held = self._held_stack()
        if name in held:
            # remove the most recent acquisition (handles out-of-order
            # release, which threading.Lock permits)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break

    def _record_violation(self, msg: str, locked: bool = False) -> None:
        if locked:
            self._violations.append(msg)
        else:
            with self._meta:
                self._violations.append(msg)
        if self.raise_on_cycle:
            raise LockOrderViolation(msg)

    # -------------------------------------------------------------- #
    # graph

    def _find_cycle(
        self, src: str, dst: str, edges: Optional[List[Tuple[str, str]]] = None
    ) -> Optional[List[str]]:
        """Path src -> ... -> dst in the edge graph (caller just added
        dst -> src, so such a path closes a cycle)."""
        edge_list = list(self._edges) if edges is None else edges
        stack = [(src, [src])]
        seen: Set[str] = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for (a, b) in edge_list:
                if a == node:
                    stack.append((b, path + [b]))
        return None

    def _cycle_message(self, path: List[str], new_site: str) -> str:
        full = [path[-1]] + path   # dst -> src ... -> dst
        arrows = " -> ".join(full)
        sites = []
        for a, b in zip(full, full[1:]):
            sites.append(f"  {a} -> {b} first seen at {self._edges.get((a, b))}")
        return (
            f"lock-order inversion: cycle {arrows}\n"
            + "\n".join(sites)
            + f"\n  closing edge acquired at {new_site}"
        )

    # -------------------------------------------------------------- #
    # inspection

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._meta:
            return dict(self._edges)

    def violations(self) -> List[str]:
        with self._meta:
            return list(self._violations)

    def cycles(self) -> List[List[str]]:
        """All elementary order cycles currently in the graph."""
        with self._meta:
            edges = list(self._edges)
        out: List[List[str]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        for (a, b) in edges:
            path = self._find_cycle(b, a, edges)
            if path is not None:
                cyc = [a] + path
                # canonicalize rotation so each cycle reports once
                nodes = cyc[:-1] if cyc[0] == cyc[-1] else cyc
                k = min(range(len(nodes)), key=lambda i: nodes[i])
                canon = tuple(nodes[k:] + nodes[:k])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    out.append(list(canon))
        return out

    def assert_no_cycles(self) -> None:
        vio = self.violations()
        cyc = self.cycles()
        if vio or cyc:
            raise LockOrderViolation(
                "lock-order violations:\n"
                + "\n".join(vio)
                + ("\ncycles: " + repr(cyc) if cyc else "")
            )


def instrument_master(
    recorder: LockOrderRecorder,
    membership=None,
    dispatcher=None,
    process_manager=None,
    servicer=None,
    evaluation=None,
    journal=None,
    autoscaler=None,
) -> LockOrderRecorder:
    """Instrument the standard master-side locks under their canonical
    names (the chaos smoke, the fleet soak, and the lock-order tests all
    share this wiring — and EDL102's CANONICAL_LOCK_NAMES mirrors it, so
    the static lock graph and the runtime edges use one vocabulary)."""
    if membership is not None:
        recorder.instrument(membership, name="membership")
    if dispatcher is not None:
        recorder.instrument(dispatcher, name="dispatcher")
    if process_manager is not None:
        recorder.instrument(process_manager, name="process_manager")
    if servicer is not None:
        recorder.instrument(servicer, name="servicer.loss", attr="_loss_lock")
        if hasattr(servicer, "_ctrl_lock"):
            recorder.instrument(servicer, name="servicer.ctrl", attr="_ctrl_lock")
    if evaluation is not None:
        recorder.instrument(evaluation, name="evaluation")
    if journal is not None:
        recorder.instrument(journal, name="journal.file")
        if hasattr(journal, "_qcv"):
            recorder.instrument(journal, name="journal.queue", attr="_qcv")
    if autoscaler is not None:
        recorder.instrument(autoscaler, name="autoscaler")
    return recorder
