"""EDL5xx: elasticity / closed-loop-autoscaler discipline.

EDL501 rescale-action-outside-policy
    A direct instance-manager resize/evict call — `.add_worker()`,
    `.remove_worker(...)`, `.evict_worker(...)`, or
    `.kill_worker(..., relaunch=False)` (the permanent-eviction
    spelling) — outside the sanctioned modules: the autoscaler policy
    engine (master/autoscaler.py), the operator entry points
    (client/local.py, client/api.py), and the manager implementations
    themselves. ISSUE 14 made every rescale decision cost-gated,
    cooldown-bounded, and journal-replayed; an ad-hoc call site
    bypasses all three at once — it can flap against the policy's own
    actions, double-fire after a master restart (nothing journaled it),
    and spend recovery cost the goodput ledger attributes to nobody.
    Route the action through `Autoscaler`/its target adapters, or carry
    a reviewed `# edl-lint: disable=EDL501` with justification.

    Receiver gating (so unrelated `.add_worker` methods stay quiet):
    the call's receiver must be manager-ish — a name (or attribute)
    matching `manager`/`mgr`, or a local name assigned from a
    `ProcessManager(...)` / `K8sInstanceManager(...)` construction in
    the same module. `kill_worker` with `relaunch=True` (or omitted) is
    the chaos/test hook — an in-place relaunch, not a resize — and is
    not flagged.

EDL503 layout-mutation-outside-policy
    A direct embedding-layout mutation on the shard-map owner —
    `.update_replicas(...)`, `.set_hot_ids(...)`, `.begin_split()`, or
    `.begin_merge()` — outside the sanctioned modules: the layout
    policy engine (master/layout_controller.py) and the owner
    implementation itself (embedding/sharding.py). ISSUE 20 made every
    layout decision cost-gated (blocked-read-seconds), per-kind
    cooldown-bounded, and journal-replayed (`layout` records); an
    ad-hoc call site bypasses all three — it can flap against the
    controller's own actions, double-fire after a master takeover
    (nothing journaled the DECISION, only the map transition), and
    stall the read path with a migration the cost model never priced.
    Route the mutation through `LayoutController`/its target adapters,
    or carry a reviewed `# edl-lint: disable=EDL503` with
    justification. (`begin_resharding` — the worker-death re-plan — is
    NOT a layout action and stays unflagged.)

    Receiver gating mirrors EDL501: the receiver must be owner-ish — a
    name (or attribute) matching `owner`/`embedding`/`shard_map` — or a
    local name assigned from a `ShardMapOwner(...)` construction in the
    same module.

EDL502 sleep-in-simulated-time
    A bare `time.sleep(...)` (or `sleep(...)` imported from `time`)
    inside `elasticdl_tpu/fleetsim/`. The fleet simulator runs on a
    virtual clock (ISSUE 16): every delay must be an event scheduled
    via `Scheduler.after(...)` / `Scheduler.at(...)` so the clock can
    jump over it. A real sleep burns wall time inside the compressed
    run (a 600 s scenario stops finishing in seconds), dodges the
    deterministic heap ordering that makes same-seed runs digest-
    identical, and silently skews the REAL costs measured around it
    (journal fsync, poll-phase walls). Schedule the delay, or carry a
    reviewed `# edl-lint: disable=EDL502` (e.g. a deliberate wall-time
    throttle in the CLI layer, outside the simulated run).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from elasticdl_tpu.analysis.core import Finding, ModuleContext, Rule, register

#: always resize/evict, whatever the arguments
_RESIZE_METHODS = {"add_worker", "remove_worker", "evict_worker"}

#: the manager classes whose constructions track receivers
_MANAGER_CLASSES = {"ProcessManager", "K8sInstanceManager"}

#: modules where direct calls are the sanctioned path: the policy
#: engine, the operator entry points, and the implementations
_ALLOWED_SUFFIXES = (
    "master/autoscaler.py",
    "master/process_manager.py",
    "master/k8s_instance_manager.py",
    "client/local.py",
    "client/api.py",
)

_MANAGERISH = re.compile(r"(manager|mgr)", re.IGNORECASE)


def _receiver_name(expr: ast.AST) -> str:
    """The receiver's trailing name: `manager` -> manager,
    `self.instance_manager` -> instance_manager, `a.b.mgr` -> mgr."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


def _is_manager_construction(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    return name in _MANAGER_CLASSES


def _relaunch_false(call: ast.Call) -> bool:
    """kill_worker's eviction spelling: relaunch=False, literally."""
    for kw in call.keywords:
        if kw.arg == "relaunch" and isinstance(kw.value, ast.Constant):
            return kw.value.value is False
    # positional: kill_worker(wid, False, ...)
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        return call.args[1].value is False
    return False


@register
class RescaleActionOutsidePolicyRule(Rule):
    id = "EDL501"
    name = "rescale-action-outside-policy"
    doc = (
        "direct instance-manager resize/evict call outside the "
        "autoscaler policy / client entry points — bypasses the cost "
        "gate, cooldown, and decision journal"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.rel_path.endswith(_ALLOWED_SUFFIXES):
            return
        tracked = self._constructed_managers(ctx)
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            method = node.func.attr
            if method in _RESIZE_METHODS:
                evictish = True
            elif method == "kill_worker" and _relaunch_false(node):
                evictish = True
            else:
                evictish = False
            if not evictish:
                continue
            recv = _receiver_name(node.func.value)
            if not (
                recv in tracked
                or _MANAGERISH.search(recv)
                or _is_manager_construction(node.func.value)
            ):
                continue
            yield self.finding(
                ctx, node,
                f"direct {method}() on an instance manager bypasses the "
                "autoscaler's cost gate, cooldown, and decision journal; "
                "route the rescale through master/autoscaler.py (or carry "
                "a reviewed disable)",
            )

    @staticmethod
    def _constructed_managers(ctx: ModuleContext) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and _is_manager_construction(
                node.value
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
        return names


#: the four journaled layout transitions on ShardMapOwner — the whole
#: mutation surface the layout controller owns (begin_resharding is the
#: worker-death re-plan, not a layout action)
_LAYOUT_METHODS = {
    "update_replicas", "set_hot_ids", "begin_split", "begin_merge",
}

#: modules where direct layout calls are the sanctioned path
_LAYOUT_ALLOWED_SUFFIXES = (
    "master/layout_controller.py",
    "embedding/sharding.py",
)

_OWNERISH = re.compile(r"(owner|embedding|shard_map)", re.IGNORECASE)


def _is_owner_construction(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    return name == "ShardMapOwner"


@register
class LayoutMutationOutsidePolicyRule(Rule):
    id = "EDL503"
    name = "layout-mutation-outside-policy"
    doc = (
        "direct shard-map layout mutation outside the layout policy "
        "engine — bypasses the cost gate, per-kind cooldown, and "
        "journaled decision history"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.rel_path.endswith(_LAYOUT_ALLOWED_SUFFIXES):
            return
        tracked = self._constructed_owners(ctx)
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LAYOUT_METHODS
            ):
                continue
            recv = _receiver_name(node.func.value)
            if not (
                recv in tracked
                or _OWNERISH.search(recv)
                or _is_owner_construction(node.func.value)
            ):
                continue
            yield self.finding(
                ctx, node,
                f"direct {node.func.attr}() on the shard-map owner "
                "bypasses the layout controller's cost gate, per-kind "
                "cooldown, and journaled decision history; route the "
                "mutation through master/layout_controller.py (or carry "
                "a reviewed disable)",
            )

    @staticmethod
    def _constructed_owners(ctx: ModuleContext) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and _is_owner_construction(
                node.value
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
        return names


#: the virtual-time package: every module under here runs (or builds
#: objects that run) inside the scenario scheduler's compressed clock
_FLEETSIM_PREFIX = "elasticdl_tpu/fleetsim/"


def _is_time_sleep(node: ast.Call, time_sleep_names: Set[str]) -> bool:
    """`time.sleep(...)` / `<alias>.sleep(...)` where the receiver is
    the `time` module, or a bare `sleep(...)` imported from `time`."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "sleep":
        return isinstance(f.value, ast.Name) and f.value.id in time_sleep_names
    if isinstance(f, ast.Name):
        return f.id in time_sleep_names and f.id != "time"
    return False


@register
class SleepInSimulatedTimeRule(Rule):
    id = "EDL502"
    name = "sleep-in-simulated-time"
    doc = (
        "bare time.sleep inside the fleet simulator — burns wall time "
        "the virtual clock is supposed to jump over and breaks "
        "same-seed determinism; schedule the delay on the event heap"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if _FLEETSIM_PREFIX not in ctx.rel_path:
            return
        names = self._time_module_names(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_time_sleep(node, names):
                yield self.finding(
                    ctx, node,
                    "time.sleep() inside elasticdl_tpu/fleetsim/ burns "
                    "real wall time in a virtual-clock run and breaks "
                    "same-seed determinism; schedule the delay via "
                    "Scheduler.after()/at() (or carry a reviewed disable)",
                )

    @staticmethod
    def _time_module_names(ctx: ModuleContext) -> Set[str]:
        """Names that resolve to the `time` module or its `sleep`:
        `import time` / `import time as t` / `from time import sleep
        [as snooze]`."""
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        names.add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        names.add(a.asname or a.name)
        return names
