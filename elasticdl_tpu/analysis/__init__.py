"""edl-lint: project-specific static analysis for the elastic control plane.

Three rule families, each encoding a hazard class this codebase has been
bitten by (or is structurally exposed to — see ISSUE 2 / PAPERS.md:
ElasWave 2510.00606 and the multi-tenant elastic-GPU study 1909.11985 both
attribute elastic-training incidents to unchecked concurrency and
recompilation):

- lock discipline (EDL1xx): `# guarded_by: _lock` attribute annotations,
  verified so every access happens under `with self._lock` or in a method
  annotated as holding it (EDL101); plus the whole-program half built on
  the project call graph (`callgraph.py` / `concurrency.py`) — static
  lock-order inversion over interprocedurally-propagated held sets
  (EDL102, `--lock-graph` emits the acquisition graph), blocking calls
  under a lock with may-block propagation (EDL103), and guarded mutable
  state escaping its critical section as a live reference (EDL104);
- JAX hazards (EDL2xx): host syncs in dispatch loops, jit cache churn,
  tracer leaks, unordered iteration feeding pytrees;
- RPC / control-plane hygiene (EDL3xx): bare stubs bypassing
  RetryingMasterStub, deadline-less RPCs, silent exception swallows,
  unjittered retry sleeps.

Run `python -m elasticdl_tpu.analysis` (text or --json output; suppress a
single finding with `# edl-lint: disable=RULE`, tolerate legacy debt via
the checked-in baseline). The runtime half — the lock-order recorder used
by the chaos tests — lives in `lockorder.py`.
"""

from elasticdl_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleContext,
    ProjectContext,
    ProjectRule,
    Rule,
    all_rules,
    load_baseline,
    prune_baseline,
    run_analysis,
)
from elasticdl_tpu.analysis.lockorder import (  # noqa: F401
    LockOrderRecorder,
    LockOrderViolation,
)
