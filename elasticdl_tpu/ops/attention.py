"""Sequence/context-parallel attention: ring attention and Ulysses all-to-all.

Net-new relative to the reference (william-wang/elasticdl is recsys/CNN
oriented and has no attention or sequence scaling anywhere — SURVEY §5), but
first-class here: long-context training must shard the SEQUENCE dimension
once activations (B, T, H, D) outgrow one chip's HBM.

Two standard TPU-native strategies over a `seq` mesh axis, both pure
`shard_map` + XLA collectives over ICI:

- **ring attention** (`mode="ring"`): K/V blocks rotate around the ring via
  `lax.ppermute` while each device streams them against its resident Q
  block using the online-softmax (flash-attention) recurrence. Peak memory
  is one KV block; comm is n-1 block transfers fully overlappable with the
  block matmuls.
- **Ulysses** (`mode="ulysses"`): `lax.all_to_all` re-shards heads<->sequence
  so each device holds the FULL sequence for H/n heads, runs ordinary
  attention locally, and all-to-alls back. Cheaper comm for moderate T,
  needs heads % seq_shards == 0.

Everything differentiates through `jax.grad` (scan + ppermute/all_to_all are
linear/differentiable), so no custom VJP is needed; accumulation runs in
float32 regardless of input dtype.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from elasticdl_tpu.common import jax_compat

jax_compat.ensure()  # older-jax API adapters (no-op on current jax)
from jax import lax
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis

NEG_BIG = -1e30  # finite "-inf": avoids nan from (-inf) - (-inf) in softmax


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   q_offset: int = 0, kv_offset: int = 0) -> jax.Array:
    """Plain softmax attention. q,k,v: (B, T, H, D). The offsets position the
    local q/kv blocks in the GLOBAL sequence for causal masking (used by the
    sequence-parallel paths; leave 0 for unsharded attention).

    On TPU this dispatches to the Pallas flash kernel
    (ops/pallas_attention.py) when shapes/offsets allow — 3-6x faster
    fwd+bwd on a v5e and O(T) memory instead of the materialized (B,H,T,T)
    score matrix. EDL_FLASH=0 forces this XLA fallback everywhere.

    Backend-divergence caveat: for a FULLY-masked row (possible only with
    offset geometries where kv_offset > q_offset + Tq - 1) the kernel
    returns zeros while this XLA path returns the uniform softmax over
    NEG_BIG scores. No in-tree caller produces such rows (the
    sequence-parallel paths always include the diagonal); external callers
    passing exotic offsets should not rely on either value."""
    from elasticdl_tpu.ops import pallas_attention

    if pallas_attention.can_flash(q.shape, k.shape, q_offset, kv_offset,
                                  dtype=q.dtype):
        return pallas_attention.flash_attention(
            q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = kv_offset + jnp.arange(k.shape[1])
        mask = kv_pos[None, :] <= q_pos[:, None]
        s = jnp.where(mask[None, None], s, NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _ring_scan(k, v, axis_name: str, manual_axes, consume, carry0):
    """The shared ring rotation: consume the resident KV block, then rotate
    KV around the ring with `ppermute` n-1 times, calling
    `consume(carry, kb, vb, kv_block)` on each visiting block.

    Invariant kept in ONE place for both ring bodies: permute FIRST inside
    the scan — the resident block was consumed before the scan starts, so
    only n-1 rotations cross the ring (no discarded final transfer) — and
    scan carries are marked "varying" over the manual mesh axes like k/v.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)

    def block(state, _):
        carry, kb, vb, j = state
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        carry = consume(carry, kb, vb, (idx - j) % n)
        return (carry, kb, vb, j + 1), None

    mark = lambda x: lax.pcast(x, tuple(manual_axes), to="varying")
    carry = consume(jax.tree_util.tree_map(mark, carry0), k, v, idx)
    if n > 1:
        (carry, _, _, _), _ = lax.scan(
            block, (carry, k, v, mark(jnp.int32(1))), None, length=n - 1
        )
    return carry


def _ring_attention_sharded(q, k, v, axis_name: str, causal: bool,
                            manual_axes=()):
    """Per-shard body (inside shard_map): q,k,v are the LOCAL seq blocks."""
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = D ** -0.5
    qf = q.astype(jnp.float32)

    q_pos = idx * Lq + jnp.arange(Lq)

    def accumulate(carry, kb, vb, kv_block):
        """One online-softmax update against KV block `kv_block`."""
        o, m, l = carry
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)) * scale
        if causal:
            kv_pos = kv_block * Lk + jnp.arange(Lk)
            mask = kv_pos[None, :] <= q_pos[:, None]           # (Lq, Lk)
            s = jnp.where(mask[None, None], s, NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1))                 # (B,H,Lq)
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32)
        )
        return o_new, m_new, l_new

    o, m, l = _ring_scan(
        k, v, axis_name, manual_axes, accumulate,
        (jnp.zeros((B, H, Lq, D), jnp.float32),
         jnp.full((B, H, Lq), NEG_BIG, jnp.float32),
         jnp.zeros((B, H, Lq), jnp.float32)),
    )
    out = o / jnp.maximum(l, 1e-20)[..., None]                 # (B,H,Lq,D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)           # (B,Lq,H,D)


def _merge_flash_blocks(o1, lse1, o2, lse2):
    """Combine two flash partials over the same q rows: softmax-weighted by
    their logsumexps (exact — this is the associative flash-merge). o:
    (B, Lq, H, D) f32; lse: (B, H, Lq) f32. Fully-masked partials carry
    lse=NEG_BIG and weight out to 0."""
    lse_new = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse_new).transpose(0, 2, 1)[..., None]  # (B,Lq,H,1)
    w2 = jnp.exp(lse2 - lse_new).transpose(0, 2, 1)[..., None]
    return o1 * w1 + o2 * w2, lse_new


def _ring_attention_flash(q, k, v, axis_name: str, causal: bool,
                          manual_axes=()):
    """Ring attention whose per-rotation block compute is the Pallas flash
    kernel (ops/pallas_attention.py): each device streams the visiting KV
    block through flash_attention_lse with TRACED global offsets (they ride
    scalar prefetch), then merges partials by logsumexp. Scores never
    materialize even within a block, unlike the XLA recurrence above."""
    from elasticdl_tpu.ops.pallas_attention import flash_attention_lse

    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    q_off = idx * Lq

    def accumulate(carry, kb, vb, kv_block):
        o2, lse2 = flash_attention_lse(
            q, kb, vb, causal=causal,
            q_offset=q_off, kv_offset=kv_block * Lk)
        return _merge_flash_blocks(*carry, o2.astype(jnp.float32), lse2)

    # zero-weight initial carry: lse=NEG_BIG merges to "no contribution"
    o, _ = _ring_scan(
        k, v, axis_name, manual_axes, accumulate,
        (jnp.zeros((B, Lq, H, D), jnp.float32),
         jnp.full((B, H, Lq), NEG_BIG, jnp.float32)),
    )
    return o.astype(q.dtype)


def _ulysses_sharded(q, k, v, axis_name: str, causal: bool):
    """Per-shard body: all_to_all heads<->sequence, local full attention."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(f"ulysses needs heads ({H}) divisible by seq shards ({n})")

    def to_seq(x):   # (B, L, H, D) -> (B, n*L, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_heads(x):  # inverse
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    out = full_attention(qs, ks, vs, causal=causal)
    return to_heads(out)


def sequence_parallel_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = True,
    mode: str = "ring",
    axis_name: Optional[str] = None,
) -> jax.Array:
    """Attention over a sequence sharded on mesh axis `axis_name` (default:
    the ambient mesh's `seq` axis if present). q,k,v: (B, T, H, D) with T
    sharded over the seq axis. Falls back to full_attention when the mesh
    has no seq axis (single-chip or pure-DP training)."""
    mesh = jax.sharding.get_abstract_mesh()
    names = tuple(mesh.axis_names)
    axis = axis_name or (MeshAxis.SEQ if MeshAxis.SEQ in names else None)
    if axis is None or mesh.shape.get(axis, 1) == 1:
        return full_attention(q, k, v, causal=causal)

    data_ax = MeshAxis.DATA if MeshAxis.DATA in names else None
    spec = P(data_ax, axis, None, None)
    manual = tuple(a for a in (data_ax, axis) if a)
    if mode == "ring":
        from elasticdl_tpu.ops import pallas_attention

        # shard-LOCAL block shapes decide whether the flash kernel applies
        seq_shards = mesh.shape[axis]
        local = (q.shape[0], q.shape[1] // seq_shards) + q.shape[2:]
        if pallas_attention.can_flash(local, local, dtype=q.dtype):
            body = partial(
                _ring_attention_flash, axis_name=axis, causal=causal,
                manual_axes=manual,
            )
        else:
            body = partial(
                _ring_attention_sharded, axis_name=axis, causal=causal,
                manual_axes=manual,
            )
    elif mode == "ulysses":
        body = partial(_ulysses_sharded, axis_name=axis, causal=causal)
    else:
        raise ValueError(f"unknown sequence-parallel mode {mode!r}")
    return jax.shard_map(
        body,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
