"""Pallas TPU flash-attention kernel — the fused hot path behind
`ops.attention.full_attention` and the ring-attention block compute.

Net-new relative to the reference (william-wang/elasticdl has no attention
anywhere — SURVEY §5 long-context), but central to the rebuild's transformer
path: the XLA fallback materializes the (B, H, Tq, Tk) score matrix in HBM,
which caps sequence length and burns HBM bandwidth; this kernel streams KV
blocks through VMEM with the online-softmax recurrence so scores never leave
the chip's vector memory, and the backward recomputes them blockwise
(flash-attention style) instead of saving them.

Layout: the public contract is (B, T, H, D) like `full_attention`; the
kernel internally works on (B, H, T, D) because Mosaic requires the last two
block dims to be (8·k, 128·k)-tiled or full — a per-head (…, 1, D) block in
the (B, T, H, D) layout violates that. The only residual saved is the
logsumexp, lane-broadcast to (B, H, Tq, 128) (TPU scratch/IO wants a 128
lane minor); `delta = rowsum(do·o)` is recomputed in-kernel from the o/do
blocks rather than stored.

`q_offset`/`kv_offset` position the local blocks in a GLOBAL sequence for
causal masking, mirroring `full_attention`'s contract. They enter the kernel
as SCALAR-PREFETCH values (SMEM), so they may be TRACED — ring attention
passes a different kv offset each ppermute rotation. `flash_attention_lse`
additionally returns the logsumexp, which is what lets ring attention merge
per-block flash results exactly (see ops.attention._ring_attention_flash).

Fully-masked causal blocks are skipped (`pl.when`), giving the ~2x causal
FLOP saving without dynamic shapes. Fully-masked ROWS (a q block entirely
before every kv position) return 0 with lse=NEG_BIG, unlike the XLA path's
finite-NEG_BIG uniform softmax — zero is the defensible answer, the ring
merge relies on the NEG_BIG lse, and no real caller consumes such rows.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_BIG = -1e30  # finite "-inf", matches ops.attention
_LANE = 128      # TPU lane width: minor dims of scratch/residuals

# Tuned on TPU v5 lite, T=4096 H8 D64 fwd+bwd: (256,256) 14.0ms,
# (512,512) 7.6ms, (512,1024) 5.9ms, (1024,1024) 5.5ms. Large KV blocks
# amortize the per-grid-step overhead; VMEM at (1024,1024) stays ~10 MB
# (the f32 score block dominates: bq*bk*4 = 4 MB).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def pick_block(t: int, target: int, min_block: int = 8) -> Optional[int]:
    """Largest power-of-two block <= target that divides t. `min_block` is
    the dtype's sublane tile: 8 for float32, 16 for bfloat16 (Mosaic tiles
    (8,128)/(16,128) respectively — a 16-sublane dtype with an 8-row block
    fails to compile on real TPU, which interpret-mode tests can't catch).
    None when t has no such divisor: caller falls back to the XLA path
    rather than padding."""
    b = 1
    while b * 2 <= min(t, target):
        b *= 2
    while b >= min_block:
        if t % b == 0:
            return b
        b //= 2
    return None


def _min_block(dtype) -> int:
    """Sublane tile floor for the q/k/v dtype (None -> assume float32):
    Mosaic tiles are (8,128) for 4-byte, (16,128) for 2-byte, (32,128) for
    1-byte dtypes."""
    if dtype is None:
        return 8
    itemsize = jnp.dtype(dtype).itemsize
    if itemsize == 1:
        return 32
    if itemsize == 2:
        return 16
    return 8


_INTERPRET_ENV = "EDL_FLASH_INTERPRET"
_warned_probe_broken = False


@contextlib.contextmanager
def interpret_mode():
    """Public entry for interpret-mode testing: wraps
    `pltpu.force_tpu_interpret_mode()` AND marks interpret mode active via
    `EDL_FLASH_INTERPRET` so `_interpret_active` has a signal that does not
    depend on JAX private internals. Tests use THIS, not pltpu directly."""
    prev = os.environ.get(_INTERPRET_ENV)
    os.environ[_INTERPRET_ENV] = "1"
    # Older jax has no global interpret-mode context; the env flag above is
    # the primary routing signal (every pallas_call here threads an explicit
    # interpret= from _interpret_active), so a nullcontext loses nothing.
    force = getattr(pltpu, "force_tpu_interpret_mode", contextlib.nullcontext)
    try:
        with force():
            yield
    finally:
        if prev is None:
            os.environ.pop(_INTERPRET_ENV, None)
        else:
            os.environ[_INTERPRET_ENV] = prev


def _interpret_active() -> bool:
    """True inside `interpret_mode()` / `pltpu.force_tpu_interpret_mode()`
    (tests run the Mosaic kernel on CPU there).

    Primary signal: the EDL_FLASH_INTERPRET env flag our own
    `interpret_mode()` sets — public, upgrade-proof. Secondary: the JAX
    config state behind pltpu's context manager, probed defensively (it is
    a private module); if that probe breaks after a JAX upgrade we log
    once instead of silently narrowing routing, and interpret_mode() users
    are unaffected."""
    if os.environ.get(_INTERPRET_ENV) == "1":
        return True
    global _warned_probe_broken
    try:
        from jax._src import config as _jax_config

        cm = getattr(
            _jax_config, "pallas_tpu_interpret_mode_context_manager", None
        )
        if cm is None:
            raise AttributeError(
                "pallas_tpu_interpret_mode_context_manager missing"
            )
        return cm.value is not None
    except Exception as e:
        if not _warned_probe_broken:
            _warned_probe_broken = True
            from elasticdl_tpu.common.log_utils import default_logger

            default_logger(__name__).warning(
                "interpret-mode probe of jax._src.config failed (%s); "
                "bare force_tpu_interpret_mode() is now invisible — use "
                "elasticdl_tpu.ops.pallas_attention.interpret_mode()", e,
            )
        return False


def _causal_p_mask(p, q_start, kv_start, block_q, block_k):
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return jnp.where(kv_pos <= q_pos, p, 0.0) if p is not None else kv_pos <= q_pos


def _sds(shape, dtype, like):
    """ShapeDtypeStruct that propagates `like`'s varying-mesh-axes set —
    required for pallas_call outputs inside a shard_map manual region
    (check_vma insists outputs declare their variance)."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------- forward


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_scr, l_scr, *, scale, causal, block_q, block_k,
                num_kv):
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)

    q_start = q_off + i * block_q
    kv_start = kv_off + j * block_k
    # causal: skip KV blocks entirely above the diagonal (traced predicate
    # — offsets come from SMEM, so this is runtime block skipping)
    live = True if not causal else kv_start <= q_start + block_q - 1

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]                             # (bq, D)
        k = k_ref[0, 0]                             # (bk, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # (bq, bk)
        if causal:
            mask = _causal_p_mask(None, q_start, kv_start, block_q, block_k)
            s = jnp.where(mask, s, NEG_BIG)

        m_prev = m_scr[:, :1]                       # (bq, 1)
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                      # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)             # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # lse of a fully-masked row: m stays NEG_BIG and l stays 0 -> the
        # log floor keeps it at ~NEG_BIG, which the ring merge treats as
        # "no contribution"
        lse_ref[0, 0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30)), lse_ref.shape[2:]
        )


def _flash_fwd(offs, qt, kt, vt, *, causal, bq, bk, interpret):
    """offs: (2,) int32 [q_off, kv_off]; qt/kt/vt: (B, H, T, D)."""
    B, H, Tq, D = qt.shape
    Tk = kt.shape[2]
    num_q, num_kv = Tq // bq, Tk // bk
    scale = D ** -0.5

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, num_kv=num_kv,
    )
    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j, offs: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j, offs: (b, h, j, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H, num_q, num_kv),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[
            q_spec,
            pl.BlockSpec((1, 1, bq, _LANE),
                         lambda b, h, i, j, offs: (b, h, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds(qt.shape, qt.dtype, qt),
            _sds((B, H, Tq, _LANE), jnp.float32, qt),
        ],
        interpret=interpret,
    )(offs, qt, kt, vt)
    return out, lse


# ---------------------------------------------------------------- backward


def _p_and_ds(q, k, v, do, lse, delta, *, scale, causal, q_start, kv_start,
              block_q, block_k):
    """Recompute the (bq, bk) p block from saved lse, and ds = p*(dp-delta).
    lse/delta: (bq, 1) float32."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    p = jnp.exp(s - lse)
    if causal:
        p = _causal_p_mask(p, q_start, kv_start, block_q, block_k)
    dp = jax.lax.dot_general(
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                               # (bq, bk)
    ds = p * (dp - delta)
    return p, ds


def _bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                   glse_ref, dq_ref, dq_acc, delta_scr, *, scale, causal,
                   block_q, block_k, num_kv):
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)
        do = do_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        # dL/ds = p*(dp - delta) + g_lse*p = p*(dp - (delta - g_lse)):
        # the lse cotangent folds into delta (dlse/ds_k = p_k)
        delta_scr[:] = jnp.broadcast_to(
            jnp.sum(do * o, axis=-1, keepdims=True)
            - (glse_ref[0, 0, :, :1] if glse_ref is not None else 0.0),
            delta_scr.shape)

    q_start = q_off + i * block_q
    kv_start = kv_off + j * block_k
    live = True if not causal else kv_start <= q_start + block_q - 1

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        _, ds = _p_and_ds(
            q, k, v_ref[0, 0], do, lse_ref[0, 0, :, :1], delta_scr[:, :1],
            scale=scale, causal=causal, q_start=q_start, kv_start=kv_start,
            block_q=block_q, block_k=block_k)
        dq_acc[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == num_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    glse_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                    causal, block_q, block_k, num_q):
    kv = pl.program_id(2)
    qi = pl.program_id(3)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = q_off + qi * block_q
    kv_start = kv_off + kv * block_k
    live = True if not causal else kv_start <= q_start + block_q - 1

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        delta = jnp.sum(do * o, axis=-1, keepdims=True)   # (bq, 1)
        if glse_ref is not None:
            delta = delta - glse_ref[0, 0, :, :1]
        p, ds = _p_and_ds(
            q, k, v_ref[0, 0], do, lse_ref[0, 0, :, :1], delta,
            scale=scale, causal=causal, q_start=q_start, kv_start=kv_start,
            block_q=block_q, block_k=block_k)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # (bk, D)
        dk_acc[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                          # (bk, D)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(res, g, g_lse, *, causal, bq, bk, interpret):
    """g: cotangent of out (B, T, H, D); g_lse: cotangent of lse (B, H, Tq)
    or None (out-only variant)."""
    offs, qt, kt, vt, ot, lse = res              # (B, H, T, D) / lse 4D
    B, H, Tq, D = qt.shape
    Tk = kt.shape[2]
    num_q, num_kv = Tq // bq, Tk // bk
    scale = D ** -0.5
    gt = g.transpose(0, 2, 1, 3)                 # (B, H, Tq, D)
    with_glse = g_lse is not None
    extra = ()
    if with_glse:
        extra = (jnp.broadcast_to(
            g_lse.astype(jnp.float32)[..., None], (B, H, Tq, _LANE)),)

    def dq_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                  *rest):
        glse_ref, tail = (rest[0], rest[1:]) if with_glse else (None, rest)
        _bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, do_ref,
                       lse_ref, glse_ref, *tail, scale=scale, causal=causal,
                       block_q=bq, block_k=bk, num_kv=num_kv)

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j, offs: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j, offs: (b, h, j, 0))
    lse_spec = pl.BlockSpec((1, 1, bq, _LANE),
                            lambda b, h, i, j, offs: (b, h, i, 0))

    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, num_q, num_kv),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec]
            + ([lse_spec] if with_glse else []),
            out_specs=[q_spec],
            scratch_shapes=[
                pltpu.VMEM((bq, D), jnp.float32),
                pltpu.VMEM((bq, _LANE), jnp.float32),
            ],
        ),
        out_shape=[_sds(qt.shape, qt.dtype, qt)],
        interpret=interpret,
    )(offs, qt, kt, vt, ot, gt, lse, *extra)[0]

    # dk/dv sweep: kv block outer (revisited output), q block inner
    def dkv_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                   *rest):
        glse_ref, tail = (rest[0], rest[1:]) if with_glse else (None, rest)
        _bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, do_ref,
                        lse_ref, glse_ref, *tail, scale=scale, causal=causal,
                        block_q=bq, block_k=bk, num_q=num_q)

    q_spec2 = pl.BlockSpec((1, 1, bq, D), lambda b, h, x, y, offs: (b, h, y, 0))
    kv_spec2 = pl.BlockSpec((1, 1, bk, D), lambda b, h, x, y, offs: (b, h, x, 0))
    lse_spec2 = pl.BlockSpec((1, 1, bq, _LANE),
                             lambda b, h, x, y, offs: (b, h, y, 0))
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, num_kv, num_q),
            in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, q_spec2,
                      lse_spec2] + ([lse_spec2] if with_glse else []),
            out_specs=[kv_spec2, kv_spec2],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ],
        ),
        out_shape=[
            _sds(kt.shape, kt.dtype, kt),
            _sds(vt.shape, vt.dtype, vt),
        ],
        interpret=interpret,
    )(offs, qt, kt, vt, ot, gt, lse, *extra)

    back = lambda x: x.transpose(0, 2, 1, 3)
    return None, back(dq), back(dk), back(dv)


# ---------------------------------------------------------------- public


@functools.lru_cache(maxsize=None)
def _make_flash(causal: bool, bq: int, bk: int, interpret: bool,
                with_lse: bool):
    """Returns flash(offs, q, k, v) -> out, or (out, lse(B, H, Tq)) when
    `with_lse` — the lse variant also backpropagates lse's cotangent (the
    ring merge differentiates through it)."""

    def _fwd_transposed(offs, q, k, v):
        qt = q.transpose(0, 2, 1, 3)
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
        out, lse = _flash_fwd(offs, qt, kt, vt, causal=causal, bq=bq, bk=bk,
                              interpret=interpret)
        return (offs, qt, kt, vt, out, lse)

    @jax.custom_vjp
    def flash(offs, q, k, v):
        res = _fwd_transposed(offs, q, k, v)
        out = res[4].transpose(0, 2, 1, 3)
        return (out, res[5][..., 0]) if with_lse else out

    def fwd(offs, q, k, v):
        res = _fwd_transposed(offs, q, k, v)
        out = res[4].transpose(0, 2, 1, 3)
        return ((out, res[5][..., 0]) if with_lse else out), res

    def bwd(res, ct):
        g, g_lse = ct if with_lse else (ct, None)
        return _flash_bwd(res, g, g_lse, causal=causal, bq=bq, bk=bk,
                          interpret=interpret)

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention_lse(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = True,
    q_offset=0, kv_offset=0,
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Flash attention over (B, T, H, D) q/k/v returning (out, lse) with
    lse (B, H, Tq) float32. Offsets may be Python ints OR traced int32
    scalars (they ride scalar prefetch). Raises ValueError when the shapes
    can't be blocked — use `can_flash` first."""
    flash, offs = _plan_call(q, k, causal, q_offset, kv_offset,
                             block_q, block_k, interpret, with_lse=True)
    return flash(offs, q, k, v)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = True,
    q_offset=0, kv_offset=0,
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Same contract as `ops.attention.full_attention` (output only; the
    cheaper backward — no lse cotangent input)."""
    flash, offs = _plan_call(q, k, causal, q_offset, kv_offset,
                             block_q, block_k, interpret, with_lse=False)
    return flash(offs, q, k, v)


def _plan_call(q, k, causal, q_offset, kv_offset, block_q, block_k,
               interpret, with_lse):
    if interpret is None:
        # default = the ambient interpret signal: on new jax the global
        # force_tpu_interpret_mode config also catches interpret=False, but
        # older jax has no global mode — the explicit flag must carry it
        interpret = _interpret_active()
    blocks = _plan_blocks(q.shape, k.shape, block_q, block_k,
                          dtype=q.dtype)
    if blocks is None:
        raise ValueError(
            f"flash_attention cannot block Tq={q.shape[1]}, Tk={k.shape[1]} "
            f"dtype={q.dtype} (need a power-of-two divisor >= "
            f"{_min_block(q.dtype)})")
    bq, bk = blocks
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(kv_offset, jnp.int32)])
    return _make_flash(bool(causal), bq, bk, bool(interpret),
                       bool(with_lse)), offs


def _plan_blocks(q_shape: Tuple[int, ...], k_shape: Tuple[int, ...],
                 block_q: int, block_k: int,
                 dtype=None) -> Optional[Tuple[int, int]]:
    mb = _min_block(dtype)
    bq = pick_block(q_shape[1], block_q, mb)
    bk = pick_block(k_shape[1], block_k, mb)
    if bq is None or bk is None:
        return None
    return bq, bk


def can_flash(q_shape: Tuple[int, ...], k_shape: Tuple[int, ...],
              q_offset=0, kv_offset=0, dtype=None) -> bool:
    """True when flash_attention supports these shapes/dtype AND a backend
    that can run the Mosaic kernel is active: real TPU, or CPU inside
    `force_tpu_interpret_mode` (tests). EDL_FLASH=0 force-disables;
    EDL_FLASH=1 force-enables but ONLY on those backends — on plain CPU/GPU
    the kernel has no compile path, so forcing it there would crash rather
    than fall back. Offsets may be traced — they are accepted for API
    symmetry and ignored."""
    del q_offset, kv_offset
    flag = os.environ.get("EDL_FLASH", "")
    if flag == "0":
        return False
    if _plan_blocks(q_shape, k_shape, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                    dtype=dtype) is None:
        return False
    runnable = jax.default_backend() == "tpu" or _interpret_active()
    if flag == "1":
        return runnable
    return jax.default_backend() == "tpu"
