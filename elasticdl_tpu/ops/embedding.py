"""Mesh-sharded embedding lookup — the HBM replacement for the parameter
server's embedding tables.

Reference parity: the reference stores embedding tables as per-PS-pod hash
maps (elasticdl/pkg/ps/embedding.go), shards rows by `id % ps_num`
(elasticdl/python/worker/ps_client.py), and pays two gRPC round-trips per
minibatch to pull vectors and push sparse gradients
(elasticdl/python/worker/worker.py → pull_embedding_vectors/push_gradients).

Rebuilt TPU-native: the table is ONE `jax.Array` whose rows are sharded
contiguously over every mesh axis. Lookup and gradient scatter-add happen
*inside* the jitted train step, so "pull" and "push" become ICI collectives:

  manual mode (shard_map):
    all_gather(ids over data axis)         # tiny int32 traffic
    local dense gather on each row shard   # MXU-friendly, static shapes
    psum_scatter(partials over data axis)  # returns each device its batch rows
    psum(over model axis)                  # combine row-shard contributions
  backward is the exact transpose (autodiff through shard_map): all_gather of
  output grads + local scatter-add into the row shard.

  auto mode: `jnp.take` on the sharded table; XLA's SPMD partitioner inserts
  an equivalent collective schedule. Kept as the fallback/baseline; `manual`
  makes the schedule explicit and predictable.

Lazy row materialization (reference: EmbeddingTable lazy-init on first pull)
is replaced by full-table initialization at state-creation time, shard-wise on
each device — XLA wants static shapes, and hashed/mod vocab (see
preprocessing.hashing) bounds the table like the reference's Hashing layer.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


@jax.custom_vjp
def gather_rows(table: jax.Array, ids: jax.Array) -> jax.Array:
    """`table[ids]` whose BACKWARD avoids XLA's unsorted scatter-add.

    Why: on TPU, XLA lowers the take-VJP's unsorted scatter-add essentially
    row-serially — measured round 3 (honest timing): 213k-row gather from a
    2.6M x 16 table runs at 46M rows/s, but its backward scatter at 0.18M
    rows/s, making the embedding UPDATE ~250x slower than the lookup and
    binding the whole DeepFM step. Two replacement strategies, selected by
    EDL_EMB_SCATTER (read at trace time):

    - `sorted` (default): argsort the ids (a fast TPU sort) and accumulate
      the full table gradient with `segment_sum(indices_are_sorted=True)` —
      a contiguous, vectorizable, scatter-free update that writes all V
      rows.
    - `unique`: sort, then compact duplicate ids into per-unique buckets
      (boundary cumsum + sorted segment_sum over at most B·L segments) and
      apply ONE scatter-add with provably `unique_indices=True` — no
      collision handling, and the dense write is V zeros + B·L touched
      rows instead of a V-row segment_sum. Wins when V >> batch.
    - `xla`: the plain take VJP (baseline for the bench comparison).
    """
    return jnp.take(table, ids, axis=0)


def _gather_rows_fwd(table, ids):
    return gather_rows(table, ids), (
        ids, jnp.empty((0,), table.dtype), table.shape[0],
    )


def _gather_rows_bwd(res, ct):
    ids, proto, num_rows = res
    # int32: the unique path's empty-segment sentinel relies on signed
    # comparisons (an unsigned dtype would make `uids < 0` vacuous and
    # collide sentinel rows at 0); vocab sizes are far below 2^31
    flat = ids.reshape(-1).astype(jnp.int32)
    cf = ct.reshape(-1, ct.shape[-1]).astype(jnp.float32)
    if flat.shape[0] == 0:  # static: empty batch, zero gradient
        return jnp.zeros((num_rows, ct.shape[-1]), proto.dtype), None
    order = jnp.argsort(flat)
    sf = flat[order]
    if os.environ.get("EDL_EMB_SCATTER", "sorted") == "unique":
        # compact duplicates: segment j = the j-th distinct id in sorted
        # order; `starts` marks each first occurrence, cumsum numbers them
        n = sf.shape[0]
        starts = jnp.concatenate(
            [jnp.ones((1,), bool), sf[1:] != sf[:-1]])
        seg = jnp.cumsum(starts) - 1                       # sorted, compact
        sums = jax.ops.segment_sum(
            cf[order], seg, num_segments=n, indices_are_sorted=True)
        uids = jax.ops.segment_max(
            sf, seg, num_segments=n, indices_are_sorted=True)
        # empty trailing segments come back at the dtype minimum; route
        # each to a DISTINCT out-of-range row (num_rows + position) so
        # mode="drop" discards them without ever violating the
        # unique_indices promise below — duplicate OOB targets would make
        # the scatter implementation-defined on TPU
        uids = jnp.where(uids < 0, num_rows + jnp.arange(n), uids)
        d_table = jnp.zeros((num_rows, cf.shape[1]), jnp.float32)
        d_table = d_table.at[uids].add(
            sums, mode="drop", unique_indices=True)
    else:
        d_table = jax.ops.segment_sum(
            cf[order], sf, num_segments=num_rows,
            indices_are_sorted=True,
        )
    return d_table.astype(proto.dtype), None


gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


def _take(table: jax.Array, ids: jax.Array) -> jax.Array:
    if os.environ.get("EDL_EMB_SCATTER", "sorted") == "xla":
        return jnp.take(table, ids, axis=0)
    return gather_rows(table, ids)

# Table rows are padded to a multiple of this so every device of any mesh up
# to this many chips gets an equal shard (shard_map needs even shards).
VOCAB_ALIGN = 256


def padded_vocab(vocab_size: int, align: int = VOCAB_ALIGN) -> int:
    return ((vocab_size + align - 1) // align) * align


def ambient_axes() -> Tuple[str, ...]:
    """Mesh axis names of the ambient `jax.set_mesh` context ('' if none)."""
    mesh = jax.sharding.get_abstract_mesh()
    return tuple(mesh.axis_names)


def table_partition_axes(axes: Optional[Sequence[str]] = None) -> Tuple[str, ...]:
    """Axes that shard embedding rows: every ambient mesh axis, in order."""
    if axes is not None:
        return tuple(axes)
    return ambient_axes()


def embedding_lookup(
    table: jax.Array,
    ids: jax.Array,
    mode: str = "manual",
) -> jax.Array:
    """Gather rows of a mesh-sharded `table` for a batch of `ids`.

    table: (V, D) sharded P((all mesh axes), None); ids: int32 (B, ...) sharded
    P(data, None...). Returns (B, ..., D) with ids' batch sharding.
    Out-of-range ids return zero vectors (used for padding sentinels).
    """
    axes = ambient_axes()
    in_range = (ids >= 0) & (ids < table.shape[0])
    safe_ids = jnp.where(in_range, ids, 0)

    if mode == "auto" or not axes:
        out = _take(table, safe_ids)
        return jnp.where(in_range[..., None], out, 0.0)

    if mode != "manual":
        raise ValueError(f"unknown embedding lookup mode {mode!r}")

    data_ax = MeshAxis.DATA if MeshAxis.DATA in axes else axes[0]
    other_axes = tuple(a for a in axes if a != data_ax)
    mesh = jax.sharding.get_abstract_mesh()
    n_shards = 1
    for a in axes:
        n_shards *= mesh.shape[a]
    if table.shape[0] % n_shards:
        # The table's padded vocab is fixed at creation time (and baked into
        # checkpoints), but dynamic world resizing can re-form the mesh with
        # a shard count that doesn't divide it (e.g. 1792 rows over 6
        # devices). shard_map needs even shards; XLA's auto partitioner does
        # not — fall back to the auto schedule for this (rare) geometry.
        logger.warning(
            "table rows (%d) not divisible by %d shards; using auto-sharded "
            "lookup for this mesh (align the vocab via padded_vocab for the "
            "manual schedule)", table.shape[0], n_shards,
        )
        out = _take(table, safe_ids)
        return jnp.where(in_range[..., None], out, 0.0)

    ids2d = safe_ids.reshape(safe_ids.shape[0], -1)  # (B, L)

    def shard_fn(table_shard, ids_local):
        # table_shard: (V/n, D); ids_local: (B/d, L)
        all_ids = jax.lax.all_gather(ids_local, data_ax, tiled=True)  # (B, L)
        shard = jax.lax.axis_index(axes)  # linear index over all axes, row-major
        offset = shard * table_shard.shape[0]
        local = all_ids - offset
        owned = (local >= 0) & (local < table_shard.shape[0])
        part = jnp.where(
            owned[..., None], _take(table_shard, jnp.where(owned, local, 0)), 0.0
        )  # (B, L, D)
        out = jax.lax.psum_scatter(
            part, data_ax, scatter_dimension=0, tiled=True
        )  # (B/d, L, D)
        if other_axes:
            out = jax.lax.psum(out, other_axes)
        return out

    out = jax.shard_map(
        shard_fn,
        in_specs=(P(axes, None), P(data_ax, None)),
        out_specs=P(data_ax, None, None),
    )(table, ids2d)
    out = out.reshape(*safe_ids.shape, table.shape[1])
    return jnp.where(in_range[..., None], out, 0.0)


def combine(vectors: jax.Array, combiner: Optional[str], ids: jax.Array,
            weights: Optional[jax.Array] = None) -> jax.Array:
    """Bag-combine (B, L, D) lookups over L (reference: the Embedding layer's
    `combiner` for sparse bag inputs). Pad slots are marked by negative ids.

    combiner: None → (B, L, D); 'sum'|'mean'|'sqrtn' → (B, D).
    """
    if combiner is None:
        return vectors
    valid = (ids >= 0).astype(vectors.dtype)
    w = valid if weights is None else weights.astype(vectors.dtype) * valid
    weighted = vectors * w[..., None]
    s = jnp.sum(weighted, axis=-2)
    if combiner == "sum":
        return s
    denom = jnp.sum(w, axis=-1, keepdims=True)
    if combiner == "mean":
        return s / jnp.maximum(denom, 1e-9)
    if combiner == "sqrtn":
        return s / jnp.sqrt(jnp.maximum(denom, 1e-9))
    raise ValueError(f"unknown combiner {combiner!r}")
