"""Mesh-sharded embedding lookup — the HBM replacement for the parameter
server's embedding tables.

Reference parity: the reference stores embedding tables as per-PS-pod hash
maps (elasticdl/pkg/ps/embedding.go), shards rows by `id % ps_num`
(elasticdl/python/worker/ps_client.py), and pays two gRPC round-trips per
minibatch to pull vectors and push sparse gradients
(elasticdl/python/worker/worker.py → pull_embedding_vectors/push_gradients).

Rebuilt TPU-native: the table is ONE `jax.Array` whose rows are sharded
contiguously over every mesh axis. Lookup and gradient scatter-add happen
*inside* the jitted train step, so "pull" and "push" become ICI collectives:

  manual mode (shard_map):
    all_gather(ids over data axis)         # tiny int32 traffic
    local dense gather on each row shard   # MXU-friendly, static shapes
    psum_scatter(partials over data axis)  # returns each device its batch rows
    psum(over model axis)                  # combine row-shard contributions
  backward is the exact transpose (autodiff through shard_map): all_gather of
  output grads + local scatter-add into the row shard.

  auto mode: `jnp.take` on the sharded table; XLA's SPMD partitioner inserts
  an equivalent collective schedule. Kept as the fallback/baseline; `manual`
  makes the schedule explicit and predictable.

Lazy row materialization (reference: EmbeddingTable lazy-init on first pull)
is replaced by full-table initialization at state-creation time, shard-wise on
each device — XLA wants static shapes, and hashed/mod vocab (see
preprocessing.hashing) bounds the table like the reference's Hashing layer.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from elasticdl_tpu.common import jax_compat

jax_compat.ensure()  # older-jax API adapters (no-op on current jax)
import numpy as np
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


@jax.custom_vjp
def gather_rows(table: jax.Array, ids: jax.Array) -> jax.Array:
    """`table[ids]` with a BACKWARD built around the TPU scatter cliff.

    Measured on the chip (round 5, idle host, scalar-readback timing,
    213k rows x D=16 into a 2.6M-row table): the scatter-add's per-element
    cost jumps ~8x once the OUTPUT outgrows the fast zone — ~14 ns/element
    when the destination is <= ~256k rows (16 MB, VMEM-resident tiles),
    ~105 ns/element into the full 2.6M-row table — and neither the
    `indices_are_sorted` nor the `unique_indices` promise changes the slow
    lowering (22.4 ms either way; a sorted `segment_sum` over V segments
    costs the same 23 ms). The earlier round-3 "0.18M rows/s, 250x slower
    than the gather" reading conflated this with an uncommitted-input
    dispatch pathology under an ambient mesh (see BASELINE.md round-5
    notes); the honest gap is ~5x (gather 4.6 ms vs scatter 22-23 ms),
    still the single biggest line in the DeepFM step.

    Strategies, selected by EDL_EMB_SCATTER (read at trace time):

    - `pallas` (default): the Mosaic placement kernel
      (ops/pallas_scatter.py) — sort once, then one-hot matmul the sorted
      windows onto 2048-row output blocks on the MXU (13-15 ms vs 26-30
      for the XLA paths on the DeepFM shape; ~4e-6 rel accuracy via a
      two-term bf16 split). Runs on real TPU or under interpret mode;
      everywhere else (and below its size gate) it falls back to:
    - `tiled`: argsort ids, materialize the sorted gradient rows
      once (contiguous), then lax.scan over vocab tiles of <= 256k rows:
      each tile dynamic-slices a fixed window of the sorted stream
      (searchsorted tile edges) and scatter-adds INSIDE the fast zone,
      accumulating tiles into the dense gradient by dynamic-update-slice.
      Every scatter's output fits the fast zone, so the whole backward
      runs at the ~14 ns/element rate plus one sorted materialization
      (measured: 10.8 ms vs 22.4 ms flat for the bench shape). A
      data-dependent overflow guard (`lax.cond` on the max window
      population) falls back to the flat scatter for pathological skew,
      so the path is exact for every distribution.
    - `sorted`: argsort + full-table `segment_sum(indices_are_sorted=True)`
      — scatter-free but writes all V segments; measured equal to the flat
      scatter on v5e (23 ms), kept as the structural baseline.
    - `unique`: sort, compact duplicates (boundary cumsum), ONE
      unique-indices scatter — same slow zone, kept for the bench menu.
    - `xla`: the plain take VJP (the flat-scatter baseline).
    """
    return jnp.take(table, ids, axis=0)


def _gather_rows_fwd(table, ids):
    return gather_rows(table, ids), (
        ids, jnp.empty((0,), table.dtype), table.shape[0],
    )


# Fast-zone knobs for the tiled backward (see gather_rows docstring).
# tile_rows x D x 4B must stay inside the measured fast-scatter zone
# (<= ~16 MB output on v5e); 128k rows x 16 floats = 8 MB leaves headroom
# for wider embedding dims. Read at trace time so bench sweeps and tests
# can resize tiles without re-importing.
DEFAULT_TILE_ROWS = 128 * 1024
# Windows are sized at slack x the uniform expectation (hashed vocabs make
# the per-tile population near-uniform; uniform max over ~20 tiles sits
# ~4 sigma = ~4% above the mean, so 1.3x is comfortable); the cond
# fallback keeps skewed id distributions exact. Cost is per window SLOT
# (round-5 chip sweep), so the window is aligned to 256 rows, not rounded
# to a power of two — pow2 rounding nearly doubled the slot count.
DEFAULT_TILE_WINDOW_SLACK = 1.3


def _tile_rows() -> int:
    return int(os.environ.get("EDL_EMB_TILE_ROWS", str(DEFAULT_TILE_ROWS)))


def _window_slack() -> float:
    return float(os.environ.get(
        "EDL_EMB_WINDOW_SLACK", str(DEFAULT_TILE_WINDOW_SLACK)))


def _tiled_table_grad(cf, sf, num_rows):
    """Dense (num_rows, D) gradient from SORTED contributions, every
    scatter confined to the fast zone.

    cf: (N, D) f32 gradient rows already in sorted-id order; sf: (N,)
    sorted int32 ids. Scans vocab tiles of TILE_ROWS rows; tile t
    dynamic-slices a fixed W-row window of (cf, sf) starting at its
    searchsorted edge — contiguous reads, no row gathers — and
    scatter-adds into a TILE_ROWS-row zero tile (mode='drop' masks the
    window tail that belongs to later tiles), then lays tiles down with
    dynamic_update_slice. W covers the max tile population for
    near-uniform (hashed) ids; `lax.cond` falls back to one flat scatter
    when the data is skewed enough to overflow a window."""
    n, d = cf.shape
    tile_rows = _tile_rows()
    nt = -(-num_rows // tile_rows)
    # Window sizing counts ALL n contributions, including the manual shard
    # path's non-owned sentinels (they sort beyond every real id, so they
    # inflate w but never a tile's population). On an s-shard mesh each
    # shard therefore sweeps ~slack*n window slots when ~n/s would cover
    # its owned rows — the backward stays at single-chip cost rather than
    # scaling down. Known refinement: derive the owned fraction from the
    # static shard count when tracing inside shard_map.
    w = int(min(n, -(-int(max(256.0, _window_slack() * n / nt)) // 256) * 256))
    vpad = nt * tile_rows
    edges = jnp.searchsorted(
        sf, jnp.arange(0, vpad + 1, tile_rows, dtype=jnp.int32)
    ).astype(jnp.int32)

    def tiled(cf, sf):
        # Pad the sorted stream by one window so a tile's slice NEVER
        # needs a clamped start: window t is then always [monotone
        # in-range ids for tile t][ids of later tiles / pad, all of which
        # map OUT of range high] — the exact shape for which the TPU's
        # drop+sorted scatter lowering is both correct and fast. The
        # design is pinned by on-TPU evidence (round-5 pt2; CPU ignores
        # the flag so only chip numerics can police it):
        #   - clamped starts put invalid slots BEFORE valid ids and the
        #     sorted lowering silently dropped ~27k rows (13%!);
        #   - dropping `indices_are_sorted` instead was exact but 1.6x
        #     slower (31 vs 19 ms) — the fast path span-searches the
        #     sorted window and skips the OOB tail;
        #   - with padding, no masks are needed at all: stray window
        #     slots belong to later tiles, so their tile-local index is
        #     >= tile_rows and mode='drop' discards them by construction.
        # pad ids with int32 max, not vpad: callers may legally pass ids
        # beyond vpad (the manual shard path's non-owned sentinels are
        # 2x the shard size), and a pad value smaller than a real id
        # would make the window tail non-monotone under the sorted
        # promise — the silent-drop trap again
        sf_pad = jnp.concatenate(
            [sf, jnp.full((w,), jnp.iinfo(jnp.int32).max, sf.dtype)])
        cf_pad = jnp.concatenate(
            [cf, jnp.zeros((w, d), cf.dtype)])

        def body(acc, t):
            c_w = jax.lax.dynamic_slice(cf_pad, (edges[t], 0), (w, d))
            s_w = jax.lax.dynamic_slice(sf_pad, (edges[t],), (w,))
            local = s_w - t * tile_rows     # monotone; >= tile_rows drops
            tile = jnp.zeros((tile_rows, d), jnp.float32).at[local].add(
                c_w, mode="drop", indices_are_sorted=True)
            return jax.lax.dynamic_update_slice(
                acc, tile, (t * tile_rows, 0)), None

        # seed the carry from the cotangent so it carries the same
        # varying-manual-axes type as the body's output when this runs
        # inside shard_map (the manual lookup schedule) — a plain
        # jnp.zeros carry is 'unvarying' there and scan rejects the
        # mismatch; the broadcast folds away in XLA
        acc = jnp.zeros((vpad, d), jnp.float32) + cf[:1, :1] * 0.0
        acc, _ = jax.lax.scan(
            body, acc, jnp.arange(nt, dtype=jnp.int32))
        return acc[:num_rows]

    def flat(cf, sf):
        return jnp.zeros((num_rows, d), jnp.float32).at[sf].add(
            cf, mode="drop", indices_are_sorted=True)

    max_pop = jnp.max(edges[1:] - edges[:-1])
    return jax.lax.cond(max_pop <= w, tiled, flat, cf, sf)


def _compact_sorted_duplicates(cf_sorted, sf_sorted):
    """Per-distinct-id sums over a SORTED contribution stream, via
    fast-zone segment ops (both outputs are n rows, n = stream length).
    Returns (sums (n, d), uids (n,)) where slot j holds the j-th distinct
    id's total; trailing empty segments come back with uid = dtype min.
    Callers apply their own out-of-range remap (the `unique` scatter
    needs DISTINCT OOB targets for its unique_indices promise; the pallas
    dedupe path collapses everything to int32max) — keep those strategies
    at the call sites, not here."""
    n = sf_sorted.shape[0]
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sf_sorted[1:] != sf_sorted[:-1]])
    seg = jnp.cumsum(is_start) - 1                     # compact, sorted
    sums = jax.ops.segment_sum(
        cf_sorted, seg, num_segments=n, indices_are_sorted=True)
    uids = jax.ops.segment_max(
        sf_sorted, seg, num_segments=n, indices_are_sorted=True)
    return sums, uids


def _pallas_table_grad(cf, sf, num_rows):
    """Dense gradient via the MXU one-hot placement kernel
    (ops/pallas_scatter.py) — same windowing contract as the tiled path
    (sorted stream, searchsorted block starts, lax.cond flat fallback on
    window overflow), but the per-block placement is dense matmul instead
    of fast-zone scatters."""
    from elasticdl_tpu.ops import pallas_scatter

    n, d = cf.shape
    bs = pallas_scatter.block_rows()
    nb = -(-num_rows // bs)
    vpad = nb * bs
    c = pallas_scatter.CHUNK
    # window statistics over the REAL row count: ceil-padding the block
    # count would undersize w for tables barely past the gate and
    # silently land every step on the flat branch
    per_block = _window_slack() * n * bs / num_rows
    w = int(min(-(-n // c) * c, max(c, -(-int(per_block) // c) * c)))
    # +128: window starts are aligned DOWN to 128 for Mosaic's DMA-offset
    # tiling proof, so a window may begin up to 127 rows before its
    # block's first id — the leading slop belongs to the previous block
    # and the one-hot never matches it. Then round UP to a whole number
    # of kernel chunks: the kernel iterates w // CHUNK full chunks, so a
    # ragged tail would be silently skipped — dropped gradient rows that
    # only full-scale on-TPU numerics catch (round-5 pt2, again).
    w = -(-(w + 128) // c) * c
    sf_pad = jnp.concatenate(
        [sf, jnp.full((w,), jnp.iinfo(jnp.int32).max, sf.dtype)])
    # transpose FIRST, pad on lanes: the (N, D) -> (D, N) relayout of the
    # small sorted stream fuses with the reorder gather (~0.7 ms
    # measured), while transpose-of-concat materialized a separate 2 ms
    # copy
    # depth padded to the Mosaic sublane tile (8): D=17 (deepfm's merged
    # linear column) would otherwise fail the DMA alignment check
    d8 = -(-d // 8) * 8
    cf_t = jnp.concatenate([
        jnp.concatenate([cf.T, jnp.zeros((d8 - d, n), cf.dtype)], axis=0),
        jnp.zeros((d8, w), cf.dtype),
    ], axis=1)
    edges = jnp.searchsorted(
        sf, jnp.arange(0, vpad + 1, bs, dtype=jnp.int32)
    ).astype(jnp.int32)
    starts = (edges[:-1] // 128) * 128

    def pallas_branch(cf_t, sf_pad, starts):
        from elasticdl_tpu.ops.pallas_attention import _interpret_active

        out_t = pallas_scatter.place_sorted_grads(
            cf_t, sf_pad[None, :], starts,
            num_rows=vpad, block_rows=bs, w=w, d_out=d,
            split=os.environ.get(
                "EDL_EMB_PALLAS_PRECISION", "split") != "bf16",
            group=pallas_scatter.group_blocks(),
            interpret=_interpret_active(),
        )
        # kernel emits (D, vpad) — rows on lanes, see pallas_scatter —
        # one bandwidth-class transpose restores the param layout
        return out_t[:, :num_rows].T

    def flat(cf_t, sf_pad, starts):
        del starts
        return jnp.zeros((num_rows, d), jnp.float32).at[sf_pad[:n]].add(
            cf_t[:d, :n].T, mode="drop", indices_are_sorted=True)

    def dedupe_then_place(cf_t, sf_pad, starts):
        """Skew middle path (executed only when a window overflows): a
        hot id concentrates its duplicates in ONE tile, but duplicates
        are ADJACENT in the sorted stream — compact them with fast-zone
        segment ops (n-row outputs, ~3 ms for the DeepFM shape), then
        place the per-unique sums with the same kernel. Window
        populations become DISTINCT-id counts, which hashing spreads
        near-uniformly, so real-world head skew stays on the MXU path
        (~9 ms) instead of the 22-30 ms flat scatter. A final flat
        fallback remains for adversarially CLUSTERED distinct ids."""
        del starts
        imax = jnp.iinfo(jnp.int32).max
        sums, uids = _compact_sorted_duplicates(
            cf_t[:d, :n].T, sf_pad[:n])
        # empty trailing segments (dtype min) and real out-of-range ids
        # (manual-path sentinels; their cotangents are zero) both go to
        # int32max: sorted with the pad, matching no window, dropped by
        # every placement below
        uids = jnp.where((uids < 0) | (uids >= num_rows), imax, uids)
        sf2 = jnp.concatenate([uids, jnp.full((w,), imax, jnp.int32)])
        cf2_t = jnp.concatenate([
            jnp.concatenate(
                [sums.T, jnp.zeros((d8 - d, n), sums.dtype)], axis=0),
            jnp.zeros((d8, w), sums.dtype),
        ], axis=1)
        edges2 = jnp.searchsorted(
            uids, jnp.arange(0, vpad + 1, bs, dtype=jnp.int32)
        ).astype(jnp.int32)
        starts2 = (edges2[:-1] // 128) * 128
        max_span2 = jnp.max(edges2[1:] - starts2)
        return jax.lax.cond(
            max_span2 <= w, pallas_branch, flat, cf2_t, sf2, starts2)

    # aligned-start coverage: window b must reach this block's last id.
    # Window statistics assume near-uniform ids (hashed vocab); skewed
    # data routes through the dedupe middle path above.
    max_span = jnp.max(edges[1:] - starts)
    return jax.lax.cond(
        max_span <= w, pallas_branch, dedupe_then_place,
        cf_t, sf_pad, starts)


def _gather_rows_bwd(res, ct):
    ids, proto, num_rows = res
    # int32: the unique path's empty-segment sentinel relies on signed
    # comparisons (an unsigned dtype would make `uids < 0` vacuous and
    # collide sentinel rows at 0); vocab sizes are far below 2^31
    flat = ids.reshape(-1).astype(jnp.int32)
    cf = ct.reshape(-1, ct.shape[-1]).astype(jnp.float32)
    if flat.shape[0] == 0:  # static: empty batch, zero gradient
        return jnp.zeros((num_rows, ct.shape[-1]), proto.dtype), None
    mode = os.environ.get("EDL_EMB_SCATTER", "pallas")
    if mode == "pallas":
        from elasticdl_tpu.ops import pallas_scatter

        bs_p = pallas_scatter.block_rows()
        # window cap: w scales as slack*n*bs/num_rows, and a small vocab
        # under a huge batch (just past the 2*bs gate) would demand a
        # VMEM window far beyond the kernel's ~4 MB budget — those shapes
        # route to the tiled path instead of failing Mosaic allocation
        est_w = _window_slack() * flat.shape[0] * bs_p / max(1, num_rows)
        if (pallas_scatter.runnable()
                and num_rows >= 2 * bs_p
                and flat.shape[0] >= 4096
                and est_w <= 16384):
            order = jnp.argsort(flat)
            d_table = _pallas_table_grad(cf[order], flat[order], num_rows)
            return d_table.astype(proto.dtype), None
        mode = "tiled"   # no TPU / small shapes: the XLA tiled path
    if mode == "tiled" and num_rows > 2 * _tile_rows() \
            and flat.shape[0] >= 4096:
        # below those sizes the flat scatter is already in (or near) the
        # fast zone and tiling only adds window overhead
        order = jnp.argsort(flat)
        d_table = _tiled_table_grad(cf[order], flat[order], num_rows)
        return d_table.astype(proto.dtype), None
    if mode == "tiled":
        d_table = jnp.zeros((num_rows, cf.shape[1]), jnp.float32).at[
            flat].add(cf, mode="drop")
        return d_table.astype(proto.dtype), None
    order = jnp.argsort(flat)
    sf = flat[order]
    if mode == "unique":
        n = sf.shape[0]
        sums, uids = _compact_sorted_duplicates(cf[order], sf)
        # Empty trailing segments come back at the dtype minimum, and REAL
        # out-of-range uids can also appear (the manual shard path's
        # non-owned sentinels are 2x the shard size). Route every
        # not-in-range target to a DISTINCT out-of-range row
        # (num_rows + position) so mode="drop" discards them without ever
        # violating the unique_indices promise below — duplicate OOB
        # targets (e.g. a real sentinel uid colliding with a rerouted
        # empty segment, code-review r5 pt4) would make the scatter
        # implementation-defined on TPU
        uids = jnp.where((uids < 0) | (uids >= num_rows),
                         num_rows + jnp.arange(n), uids)
        d_table = jnp.zeros((num_rows, cf.shape[1]), jnp.float32)
        d_table = d_table.at[uids].add(
            sums, mode="drop", unique_indices=True)
    else:
        d_table = jax.ops.segment_sum(
            cf[order], sf, num_segments=num_rows,
            indices_are_sorted=True,
        )
    return d_table.astype(proto.dtype), None


gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


def scatter_add_dense(
    ids: jax.Array, rows: jax.Array, num_rows: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Dense (num_rows, D) sum of `rows` placed at `ids` — the embedding
    tier's push hot path, routed through the SAME strategy menu as the
    training backward (`EDL_EMB_SCATTER`: pallas placement kernel with the
    dedupe middle path, tiled fast-zone scan, sorted segment-sum, unique
    compaction, flat XLA scatter).

    ids: int32 (N,) — out-of-range ids (negative padding sentinels,
    anything >= num_rows) are dropped, contributing nothing. rows: (N, D)
    contribution rows. The duplicates-ADD semantics match a sparse
    gradient push: duplicate ids accumulate. Empty N is a static no-op
    (zeros). This is exactly `gather_rows`'s VJP applied to an explicit
    cotangent, so every kernel-path guarantee (window guards, skew dedupe,
    bf16 split accuracy) documented there applies here unchanged."""
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    rows = jnp.asarray(rows)
    rows = rows.reshape(-1, rows.shape[-1])
    # same routing as embedding_lookup: out-of-range ids (padding
    # sentinels) go to a LARGE value so the sorted paths never pile them
    # into tile 0's window (see the lookup's oob note)
    oob = jnp.iinfo(jnp.int32).max // 2
    in_range = (ids >= 0) & (ids < num_rows)
    safe_ids = jnp.where(in_range, ids, oob)
    rows = jnp.where(in_range[:, None], rows, 0)
    d_table, _ = _gather_rows_bwd(
        (safe_ids, jnp.empty((0,), dtype), num_rows), rows
    )
    return d_table


def _take(table: jax.Array, ids: jax.Array) -> jax.Array:
    if os.environ.get("EDL_EMB_SCATTER", "pallas") == "xla":
        return jnp.take(table, ids, axis=0)
    return gather_rows(table, ids)

# Table rows are padded to a multiple of this so every device of any mesh up
# to this many chips gets an equal shard (shard_map needs even shards).
VOCAB_ALIGN = 256
# Large tables align to 8192 instead: the Pallas placement kernel emits
# whole row-blocks, and a vocab that isn't block-aligned costs a 178 MB
# epilogue slice-copy (~4 ms/step measured) to trim the padding. 8192 is
# a multiple of every power-of-two block size the kernel sweeps, so the
# alignment holds regardless of EDL_EMB_PALLAS_BS. Absolute overhead is
# bounded by 8191 extra rows (~0.5 MB at D=16).
# NOTE (round-5 geometry change): tables created before this alignment
# existed were padded to 256; their checkpoints restore only into models
# built with the same geometry (pass align=VOCAB_ALIGN explicitly to
# reproduce it). The padded vocab has always been baked into checkpoints —
# this changes which value large-vocab models bake.
PALLAS_VOCAB_MIN = 64 * 1024
PALLAS_VOCAB_ALIGN = 8192


def padded_vocab(vocab_size: int, align: Optional[int] = None) -> int:
    if align is None:
        align = (PALLAS_VOCAB_ALIGN
                 if vocab_size >= PALLAS_VOCAB_MIN else VOCAB_ALIGN)
    return ((vocab_size + align - 1) // align) * align


def geometry_descriptor() -> dict:
    """The vocab-padding rule baked into embedding-table shapes, as data.

    Checkpoints persist padded tables, so the padding rule is part of the
    checkpoint geometry: a model rebuilt under a *different* rule cannot
    restore them (orbax shape mismatch). CheckpointManager records this
    descriptor beside every checkpoint dir and compares it on a failed
    restore, turning the raw shape error into an actionable message
    ("rebuild with vocab_align=256"). `geometry_version` bumps whenever the
    rule changes: v1 = align 256 for every vocab; v2 (round 5) = 8192 for
    vocabs >= 64k.
    """
    return {
        "geometry_version": 2,
        "vocab_align": VOCAB_ALIGN,
        "pallas_vocab_align": PALLAS_VOCAB_ALIGN,
        "pallas_vocab_min": PALLAS_VOCAB_MIN,
    }


def ambient_axes() -> Tuple[str, ...]:
    """Mesh axis names of the ambient `jax.set_mesh` context ('' if none)."""
    mesh = jax.sharding.get_abstract_mesh()
    return tuple(mesh.axis_names)


def table_partition_axes(axes: Optional[Sequence[str]] = None) -> Tuple[str, ...]:
    """Axes that shard embedding rows: every ambient mesh axis, in order."""
    if axes is not None:
        return tuple(axes)
    return ambient_axes()


def embedding_lookup(
    table: jax.Array,
    ids: jax.Array,
    mode: str = "manual",
) -> jax.Array:
    """Gather rows of a mesh-sharded `table` for a batch of `ids`.

    table: (V, D) sharded P((all mesh axes), None); ids: int32 (B, ...) sharded
    P(data, None...). Returns (B, ..., D) with ids' batch sharding.
    Out-of-range ids return zero vectors (used for padding sentinels).
    """
    axes = ambient_axes()
    in_range = (ids >= 0) & (ids < table.shape[0])
    # Out-of-range ids (the negative padding sentinels of bag features) go
    # to a LARGE out-of-range value, not row 0: the forward masks them
    # either way (jnp.take clips, then in_range zeroes the vectors and
    # their cotangents), but the tiled backward sorts the raw ids — a
    # row-0 pile of pad slots would overflow tile 0's window and
    # permanently trip the flat-scatter fallback (code-review r5 pt4,
    # same pathology as the manual path's non-owned ids). int32max/2
    # stays beyond every padded vocab and survives the shard path's
    # offset subtraction without wrapping.
    oob = jnp.iinfo(jnp.int32).max // 2
    safe_ids = jnp.where(in_range, ids, oob).astype(jnp.int32)

    if mode == "manual" and axes:
        mesh_ = jax.sharding.get_abstract_mesh()
        if int(np.prod([mesh_.shape[a] for a in axes])) == 1:
            # a 1-device mesh has nothing to shard: the shard_map schedule
            # only adds manual-axes bookkeeping around the same local
            # gather/scatter (measured round 5: ~8 ms/step of pure
            # overhead in the DeepFM backward) — route to auto
            mode = "auto"

    if mode == "auto" or not axes:
        out = _take(table, safe_ids)
        return jnp.where(in_range[..., None], out, 0.0)

    if mode != "manual":
        raise ValueError(f"unknown embedding lookup mode {mode!r}")

    data_ax = MeshAxis.DATA if MeshAxis.DATA in axes else axes[0]
    other_axes = tuple(a for a in axes if a != data_ax)
    mesh = jax.sharding.get_abstract_mesh()
    n_shards = 1
    for a in axes:
        n_shards *= mesh.shape[a]
    if table.shape[0] % n_shards:
        # The table's padded vocab is fixed at creation time (and baked into
        # checkpoints), but dynamic world resizing can re-form the mesh with
        # a shard count that doesn't divide it (e.g. 1792 rows over 6
        # devices). shard_map needs even shards; XLA's auto partitioner does
        # not — fall back to the auto schedule for this (rare) geometry.
        logger.warning(
            "table rows (%d) not divisible by %d shards; using auto-sharded "
            "lookup for this mesh (align the vocab via padded_vocab for the "
            "manual schedule)", table.shape[0], n_shards,
        )
        out = _take(table, safe_ids)
        return jnp.where(in_range[..., None], out, 0.0)

    ids2d = safe_ids.reshape(safe_ids.shape[0], -1)  # (B, L)

    def shard_fn(table_shard, ids_local):
        # table_shard: (V/n, D); ids_local: (B/d, L)
        all_ids = jax.lax.all_gather(ids_local, data_ax, tiled=True)  # (B, L)
        shard = jax.lax.axis_index(axes)  # linear index over all axes, row-major
        offset = shard * table_shard.shape[0]
        local = all_ids - offset
        owned = (local >= 0) & (local < table_shard.shape[0])
        # Non-owned ids map OUT of the shard's range (not to row 0): the
        # forward clamps/masks them either way, but the backward's tiled
        # scatter sorts the raw ids — a row-0 pile of every non-owned id
        # (up to (n_shards-1)/n_shards of the batch) would overflow tile
        # 0's window and trip the lax.cond flat fallback EVERY step,
        # silently making `tiled` slower than the flat scatter on exactly
        # the multi-chip manual path it exists for (code-review r5 pt3).
        # 2x the shard size specifically: the tiled backward's padded
        # vocab is < 1.5x num_rows (tile_rows < num_rows/2 on that path),
        # so 2x sits beyond the last searchsorted edge and the sentinels
        # count toward NO tile's window population; every scatter mode
        # drops out-of-range cotangent rows.
        sentinel = jnp.int32(2 * table_shard.shape[0])
        part = jnp.where(
            owned[..., None],
            _take(table_shard, jnp.where(owned, local, sentinel)), 0.0
        )  # (B, L, D)
        out = jax.lax.psum_scatter(
            part, data_ax, scatter_dimension=0, tiled=True
        )  # (B/d, L, D)
        if other_axes:
            out = jax.lax.psum(out, other_axes)
        return out

    out = jax.shard_map(
        shard_fn,
        in_specs=(P(axes, None), P(data_ax, None)),
        out_specs=P(data_ax, None, None),
    )(table, ids2d)
    out = out.reshape(*safe_ids.shape, table.shape[1])
    return jnp.where(in_range[..., None], out, 0.0)


def combine(vectors: jax.Array, combiner: Optional[str], ids: jax.Array,
            weights: Optional[jax.Array] = None) -> jax.Array:
    """Bag-combine (B, L, D) lookups over L (reference: the Embedding layer's
    `combiner` for sparse bag inputs). Pad slots are marked by negative ids.

    combiner: None → (B, L, D); 'sum'|'mean'|'sqrtn' → (B, D).
    """
    if combiner is None:
        return vectors
    valid = (ids >= 0).astype(vectors.dtype)
    w = valid if weights is None else weights.astype(vectors.dtype) * valid
    weighted = vectors * w[..., None]
    s = jnp.sum(weighted, axis=-2)
    if combiner == "sum":
        return s
    denom = jnp.sum(w, axis=-1, keepdims=True)
    if combiner == "mean":
        return s / jnp.maximum(denom, 1e-9)
    if combiner == "sqrtn":
        return s / jnp.sqrt(jnp.maximum(denom, 1e-9))
    raise ValueError(f"unknown combiner {combiner!r}")
