"""Mixture-of-Experts with expert parallelism over an `expert` mesh axis.

Net-new relative to the reference (william-wang/elasticdl has no MoE),
completing the parallelism matrix alongside dp/tp/sp/pp: expert weights
are stacked (E, ...) and sharded one-expert-group-per-shard; tokens are
dispatched to experts through the GShard/Switch dense dispatch-mask
einsums, so XLA's SPMD partitioner lowers the token movement to
all_to_all over the expert axis — the rebuild never hand-writes the
collective (same philosophy as the tp/embedding paths).

Routing is Switch-style top-1 with a capacity bound: each expert accepts
at most `capacity_factor * tokens / E` tokens per batch; overflow tokens
pass through the residual untouched (their combine weight is zero), which
keeps every shape static — the XLA-friendly alternative to dynamic
per-expert buffers. The auxiliary load-balancing loss (Switch Transformer
eq. 4: E * Σ_e fraction_e · router_prob_e) is returned for the caller to
add to the task loss.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from elasticdl_tpu.common import jax_compat

jax_compat.ensure()  # older-jax API adapters (no-op on current jax)

from elasticdl_tpu.common.constants import MeshAxis

EXPERT_AXIS = MeshAxis.EXPERT


def switch_moe(
    x: jax.Array,        # (N, C) tokens
    wg: jax.Array,       # (C, E) router
    w1: jax.Array,       # (E, C, H)
    b1: jax.Array,       # (E, H)
    w2: jax.Array,       # (E, H, C)
    b2: jax.Array,       # (E, C)
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """Top-1 MoE over flat tokens. Returns (out (N, C), aux_loss ()).

    Dense dispatch: a (N, E, Cap) one-hot mask routes tokens into the
    static (E, Cap, C) expert buffers and combines them back scaled by
    the router probability. Dropped (over-capacity) tokens contribute 0
    — callers add the residual so they pass through unchanged.
    """
    n, c = x.shape
    e = wg.shape[1]
    cap = max(1, int(capacity_factor * n / e))

    logits = (x.astype(jnp.float32)) @ wg.astype(jnp.float32)   # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                     # (N,)
    gate = jnp.take_along_axis(
        probs, expert_idx[:, None], axis=-1)[:, 0]              # (N,)

    onehot_e = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (N, E)
    # position of each token within its expert's buffer (arrival order)
    pos = jnp.cumsum(onehot_e, axis=0) * onehot_e - 1.0          # (N, E)
    pos_tok = jnp.sum(pos * onehot_e, axis=-1)                   # (N,)
    keep = (pos_tok >= 0) & (pos_tok < cap)
    pos_clamped = jnp.clip(pos_tok, 0, cap - 1).astype(jnp.int32)

    onehot_c = jax.nn.one_hot(pos_clamped, cap, dtype=jnp.float32)  # (N, Cap)
    dispatch = (
        onehot_e[:, :, None] * onehot_c[:, None, :]
        * keep[:, None, None].astype(jnp.float32)
    )                                                            # (N, E, Cap)

    expert_in = jnp.einsum(
        "nec,nd->ecd", dispatch, x.astype(jnp.float32))          # (E, Cap, C)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edh->ech", expert_in, w1.astype(jnp.float32))
        + b1[:, None, :].astype(jnp.float32))
    expert_out = jnp.einsum(
        "ech,ehd->ecd", h, w2.astype(jnp.float32)
    ) + b2[:, None, :].astype(jnp.float32)                       # (E, Cap, C)

    combine = dispatch * gate[:, None, None]                     # (N, E, Cap)
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)

    # Switch load-balancing loss: E * sum_e (token fraction_e * mean router
    # prob_e) — 1.0 at perfect balance
    frac = jnp.mean(onehot_e, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return out.astype(x.dtype), aux


def expert_partition_names(ndim: int) -> Tuple:
    """(expert, None, ...) partitioning names for a stacked expert leaf;
    the axis only binds when the ambient mesh has it (mesh-adaptive, like
    the Embedding layer / PipelinedBlocks)."""
    mesh = jax.sharding.get_abstract_mesh()
    lead = EXPERT_AXIS if EXPERT_AXIS in mesh.axis_names else None
    return (lead,) + (None,) * (ndim - 1)
