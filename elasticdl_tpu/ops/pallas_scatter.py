"""Pallas TPU kernel for the embedding-gradient placement — the MXU
replacement for XLA's row-serial scatter-add.

Context (BASELINE.md round-5 pt 2): the embedding backward must place ~213k
sorted gradient rows into a 2.6M-row dense table. Every XLA formulation is
bound by per-ROW transaction costs — scatter-add ~14 ns/element in the
fast (<=256k-row output) zone, ~105 ns beyond it, and even dynamic-slice/
dynamic-update-slice window plumbing costs ~12-18 ns/row — so the best
XLA schedule (`EDL_EMB_SCATTER=tiled`, ops/embedding.py) still spends
~16 ms/step. This kernel reformulates placement as BLOCKED ONE-HOT MATMUL:

  grid over output row-blocks (bs rows); block b DMAs the contiguous
  window of the sorted stream that searchsorted assigned to it (scalar-
  prefetched starts), then accumulates
      out_block += one_hot(ids - b*bs) @ grads        # (bs,C) @ (C,D)
  chunk by chunk on the MXU. Sorted-stream windows are CONTIGUOUS, so the
  DMAs run at bandwidth, and the "scatter" itself becomes dense compute
  (~86 GFLOP for the DeepFM shape — ~0.5 ms of MXU time) instead of 280k
  row transactions.

Window coverage follows the tiled path's contract: the caller guarantees
(via the same lax.cond max-population guard) that no block's population
exceeds the static window W; ids beyond the caller's row range (manual-
shard sentinels, padding) simply never match the one-hot and drop out.

Reference parity note: the reference's Go PS applied sparse gradients
row-by-row in a hash map (elasticdl/pkg/ps/optimizer.go); this is that
component's hot loop, rebuilt as dense MXU math.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Output rows per grid step and sorted-stream rows per MXU chunk. bs*C
# bf16 one-hot (4 MB at 8192x256) is the VMEM high-water mark; C=256 keeps
# the contraction MXU-friendly (2x128 lanes). Total kernel work (compares
# AND matmul FLOPs) scales with vocab * window, and the window shrinks
# with the block, so smaller blocks win until grid/DMA overhead bites —
# block size is env-tunable for the bench sweep. Chip sweep (round 5,
# DeepFM shape, TRANSPOSED output): the standalone D=16/sgd update step
# measured 2048/4096/8192 -> 12.9/11.6/15.4 ms, but the FULL DeepFM
# step (D=17, adam, fwd gather in the same program) measured 589k
# samples/s at 2048 vs 560k at 4096 — the end-to-end metric wins, so
# 2048 stays the default.
DEFAULT_BLOCK_ROWS = 2048
CHUNK = 256


def block_rows() -> int:
    return int(os.environ.get(
        "EDL_EMB_PALLAS_BS", str(DEFAULT_BLOCK_ROWS)))


def _kernel(starts_ref, sf_ref, cf_ref, out_ref, ids_vmem, vec_vmem,
            sem_ids, sem_vec, *, bs, w, d, d_out, split, group):
    """`group` output blocks per grid step (default 1 — see the sweep
    note in place_sorted_grads). Sub-block indices are PYTHON ints
    (static scratch slots: the dynamic-slot double-buffer variant
    measured 5.5x SLOWER), and a step's DMAs all start before the first
    wait so multi-block groups overlap their transfers."""
    b = pl.program_id(0)

    def copies(g):
        # the caller aligns starts to 128: Mosaic must PROVE dynamic DMA
        # offsets land on tile boundaries, and both streams put the
        # window dimension on LANES — ids as a (1, N) row, gradients
        # TRANSPOSED to (D, N) (slicing the untransposed (N, D) would
        # lane-slice a 128-padded memref, which Mosaic rejects)
        start = pl.multiple_of(starts_ref[b * group + g], 128)
        return (
            pltpu.make_async_copy(
                sf_ref.at[:, pl.ds(start, w)], ids_vmem.at[g],
                sem_ids.at[g]),
            pltpu.make_async_copy(
                cf_ref.at[:, pl.ds(start, w)], vec_vmem.at[g],
                sem_vec.at[g]),
        )

    for g in range(group):
        for cp in copies(g):
            cp.start()

    for g in range(group):
        for cp in copies(g):
            cp.wait()
        base = (b * group + g) * bs
        # the accumulator is built TRANSPOSED, (D, bs): the output's
        # row dimension must ride the 128-lane axis — a (bs, 17) block
        # lane-pads 17 -> 128 in VMEM, a 7.5x write-bandwidth tax that
        # was most of the kernel's cost (write-only floor 7.5 ms) and
        # an OOM at group=8. dot_general(vec, onehot) contracting the
        # chunk gives (D, bs) natively, no in-register transpose.
        acc = jnp.zeros((d, bs), jnp.float32)
        row_ids = jax.lax.broadcasted_iota(
            jnp.int32, (bs, CHUNK), 0) + base
        for c in range(w // CHUNK):
            ids_c = ids_vmem[g, :, c * CHUNK:(c + 1) * CHUNK]    # (1, C)
            vec_c = vec_vmem[g, :, c * CHUNK:(c + 1) * CHUNK]    # (D, C)
            onehot = (row_ids == ids_c).astype(jnp.bfloat16)     # 0/1
            dims = (((1,), (1,)), ((), ()))
            if split:
                # Two-term bf16 split of the f32 gradient values: the
                # MXU runs bf16, and a single cast rounds the
                # accumulated gradients to ~8 mantissa bits (0.4% rel
                # err measured); hi+lo recovers ~16 bits (~4e-6 rel)
                # for a second matmul pass. EDL_EMB_PALLAS_PRECISION=
                # bf16 drops the second pass for models already
                # training in bf16 end to end.
                hi = vec_c.astype(jnp.bfloat16)
                lo = (vec_c - hi.astype(jnp.float32)).astype(jnp.bfloat16)
                acc = acc + jax.lax.dot_general(
                    hi, onehot, dimension_numbers=dims,
                    preferred_element_type=jnp.float32,
                ) + jax.lax.dot_general(
                    lo, onehot, dimension_numbers=dims,
                    preferred_element_type=jnp.float32,
                )
            else:
                acc = acc + jax.lax.dot_general(
                    vec_c.astype(jnp.bfloat16), onehot,
                    dimension_numbers=dims,
                    preferred_element_type=jnp.float32,
                )
        # d is the 8-aligned padded depth the DMA needs; the real
        # embedding width d_out is restored in-register before the write
        out_ref[:, g * bs:(g + 1) * bs] = acc[:d_out, :]


def group_blocks() -> int:
    g = int(os.environ.get("EDL_EMB_PALLAS_GROUP", "1"))
    if g < 1:
        raise ValueError(
            f"EDL_EMB_PALLAS_GROUP must be >= 1, got {g}")
    return g


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_rows", "block_rows", "w", "d_out", "split", "group",
        "interpret"))
def place_sorted_grads(cf, sf, starts, *, num_rows, block_rows, w,
                       d_out=None, split=True, group=1, interpret=False):
    """Dense (D, num_rows) TRANSPOSED gradient from a SORTED stream
    (the row dimension rides the 128-lane axis so output writes aren't
    lane-padded; callers transpose once at the end).

    cf: (D, N_pad) float32 gradient rows TRANSPOSED into sorted-id order
    along lanes, padded by at least `w` columns; sf: (1, N_pad) the
    matching sorted int32 ids, padded with int32max; starts:
    (num_rows/block_rows,) int32 — each block's 128-ALIGNED window start.
    Ids outside [block*bs, block*bs + bs) contribute nothing (the one-hot
    never matches), which also silently drops sentinel/padding ids and
    the aligned-start leading slop. The caller must guarantee every
    block's window span fits in `w` (lax.cond guard in ops.embedding)
    and that num_rows % block_rows == 0.
    """
    d, n_pad = cf.shape
    if d % 8:
        raise ValueError(
            f"cf depth {d} must be 8-aligned (Mosaic sublane tiling); pad "
            f"with zero rows and pass d_out")
    if w % CHUNK:
        # the kernel iterates w // CHUNK WHOLE chunks — a ragged tail
        # would be silently skipped (dropped gradient rows, caught only
        # by full-scale on-chip numerics in round 5); fail loudly instead
        raise ValueError(f"window {w} must be a multiple of CHUNK={CHUNK}")
    d_out = d if d_out is None else d_out
    bs = block_rows
    nb = num_rows // bs
    # Chip sweep (round 5, DeepFM shape, transposed out): group 1/2/4
    # all ~8.3 ms, group 8 EXPLODES to ~60 ms (VMEM-pressure spill
    # signature). The write-only "7.5 ms grid floor" that motivated
    # grouping turned out to be the lane-padded (bs, 17) write tax the
    # transposed output already removed — per-step overhead is small.
    # `group` is a STATIC arg (callers read group_blocks()) so env
    # sweeps reach the jit cache key; legalize to a divisor of nb.
    while nb % group:
        group //= 2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb // group,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (d_out, bs * group), lambda b, starts: (0, b)),
        scratch_shapes=[
            pltpu.VMEM((group, 1, w), jnp.int32),
            pltpu.VMEM((group, d, w), jnp.float32),
            pltpu.SemaphoreType.DMA((group,)),
            pltpu.SemaphoreType.DMA((group,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _kernel, bs=bs, w=w, d=d, d_out=d_out, split=split,
            group=group),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((d_out, num_rows), jnp.float32),
        interpret=interpret,
    )(starts, sf, cf)


def runnable() -> bool:
    """The kernel needs a real TPU or interpret mode (CPU tests)."""
    from elasticdl_tpu.ops.pallas_attention import _interpret_active

    return jax.default_backend() == "tpu" or _interpret_active()
