"""Kubernetes submission: render and submit the job for a TPU slice.

Reference parity: elasticdl_client/api.py (master pod spec rendering +
submission) and elasticdl/python/common/k8s_client.py (typed pod creation,
job labels, resources). Differences are deliberate and TPU-shaped:

- The reference ran CPU/GPU worker pods the master created one by one; a TPU
  slice is provisioned as a unit, so workers render as ONE headless-service
  StatefulSet (stable per-host identity → stable jax.distributed process
  ids) with `google.com/tpu` resources and a `cloud.google.com/gke-tpu-*`
  node selector, sized `num_workers` = hosts in the slice.
- The master stays a plain CPU pod serving the task queue on DCN, exactly as
  the reference's master did.
- Config still propagates by argv re-serialization (JobConfig.to_argv) in the
  pod command line, the reference's load-bearing pattern.

`submit` applies the manifests with kubectl when present, else prints them
(zero-egress sandboxes render only — the manifest IS the deliverable).
"""

from __future__ import annotations

import shutil
import subprocess
import sys
from typing import Any, Dict, List

import yaml

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import (
    DEFAULT_MASTER_PORT,
    TPU_TYPES as _TPU_TYPES,
    WorkerEnv,
)
from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)

JOB_LABEL = "elasticdl-tpu-job-name"


def _parse_resources(spec: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for item in spec.split(","):
        if "=" in item:
            k, v = item.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _env_list(cfg: JobConfig, extra: Dict[str, str]) -> List[Dict[str, str]]:
    env = {str(k): str(v) for k, v in cfg.envs.items()}
    env.update(extra)
    return [{"name": k, "value": v} for k, v in env.items()]


def render_master_pod(cfg: JobConfig) -> Dict[str, Any]:
    port = int(cfg.master_addr.rsplit(":", 1)[1]) if ":" in cfg.master_addr else DEFAULT_MASTER_PORT
    master_name = f"{cfg.job_name}-master"
    args = cfg.replace(master_addr=f"0.0.0.0:{port}").to_argv()
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": master_name,
            "namespace": cfg.namespace,
            "labels": {JOB_LABEL: cfg.job_name, "app": "elasticdl-tpu", "role": "master"},
        },
        "spec": {
            "restartPolicy": cfg.restart_policy,
            "containers": [
                {
                    "name": "master",
                    "image": cfg.image_name,
                    "imagePullPolicy": cfg.image_pull_policy,
                    "command": ["python", "-m", "elasticdl_tpu.master.main"],
                    "args": args,
                    "ports": [{"containerPort": port, "name": "grpc"}],
                    "resources": {
                        "requests": _parse_resources(cfg.master_resource_request)
                    },
                    "env": _env_list(cfg, {}),
                }
            ],
        },
    }


def render_master_service(cfg: JobConfig) -> Dict[str, Any]:
    port = int(cfg.master_addr.rsplit(":", 1)[1]) if ":" in cfg.master_addr else DEFAULT_MASTER_PORT
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{cfg.job_name}-master",
            "namespace": cfg.namespace,
            "labels": {JOB_LABEL: cfg.job_name},
        },
        "spec": {
            "selector": {JOB_LABEL: cfg.job_name, "role": "master"},
            "ports": [{"port": port, "targetPort": port, "name": "grpc"}],
        },
    }


# TPU accelerator type map — canonical copy in common/constants.py so config
# validation can reason about slice shape without this module
TPU_TYPES = _TPU_TYPES


def _tpu_scheduling(cfg: JobConfig) -> tuple:
    """Shared TPU scheduling block for both worker flavors: returns
    (node_selector, resources, hosts_in_slice or None)."""
    node_selector: Dict[str, str] = {}
    resources = _parse_resources(cfg.worker_resource_request)
    hosts = None
    if cfg.tpu_type:
        if cfg.tpu_type not in TPU_TYPES:
            raise ValueError(
                f"unknown tpu_type {cfg.tpu_type!r}; known: {sorted(TPU_TYPES)}"
            )
        accel, topology, hosts, chips = TPU_TYPES[cfg.tpu_type]
        node_selector = {
            "cloud.google.com/gke-tpu-accelerator": accel,
            "cloud.google.com/gke-tpu-topology": topology,
        }
        resources["google.com/tpu"] = str(chips)
    return node_selector, resources, hosts


def render_worker_statefulset(cfg: JobConfig) -> List[Dict[str, Any]]:
    """Workers as a StatefulSet over the TPU slice's hosts."""
    name = f"{cfg.job_name}-worker"
    master_svc = f"{cfg.job_name}-master"
    port = int(cfg.master_addr.rsplit(":", 1)[1]) if ":" in cfg.master_addr else DEFAULT_MASTER_PORT
    worker_cfg = cfg.replace(master_addr=f"{master_svc}:{port}")
    args = worker_cfg.to_argv()

    node_selector, resources, hosts = _tpu_scheduling(cfg)
    replicas = cfg.num_workers
    extra_env = {"EDL_COORDINATOR_ADDR": f"{name}-0.{name}:8471"}
    if hosts is None and cfg.num_processes > 1:
        # explicit multi-process cohort without a TPU slice pinning the host
        # count (CPU/GPU nodes, or TPU via custom selectors): one replica per
        # cohort process, ids from StatefulSet ordinals — without this the
        # world has no process ids and never forms
        replicas = cfg.num_processes
        extra_env["EDL_PROCESS_ID_FROM_HOSTNAME"] = "1"
    elif hosts == 1 and cfg.num_processes > 1:
        raise ValueError(
            f"tpu_type={cfg.tpu_type} is a single-host slice: it runs ONE "
            f"process owning all its chips (num_processes=1), got "
            f"num_processes={cfg.num_processes}"
        )
    if hosts is not None:
        if cfg.num_workers not in (1, hosts):
            logger.warning(
                "tpu_type=%s pins the worker count to its host count (%d); "
                "ignoring num_workers=%d", cfg.tpu_type, hosts, cfg.num_workers,
            )
        replicas = hosts
        if hosts > 1:
            # A multi-host slice is ONE SPMD cohort — plain workers here
            # would train `hosts` divergent replicas, the exact hole
            # JobConfig.validate closes for num_workers (the renderer must
            # enforce it too, since it, not the config, decides replicas).
            if cfg.num_processes not in (1, hosts):
                raise ValueError(
                    f"tpu_type={cfg.tpu_type} is a {hosts}-host slice: "
                    f"num_processes must be {hosts} (or 1 for auto), got "
                    f"{cfg.num_processes}"
                )
            worker_cfg = worker_cfg.replace(num_processes=hosts)
            args = worker_cfg.to_argv()
            # each pod derives its cohort process id from its StatefulSet
            # ordinal (parallel/elastic.context_from_env)
            extra_env["EDL_PROCESS_ID_FROM_HOSTNAME"] = "1"

    headless = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": cfg.namespace,
            "labels": {JOB_LABEL: cfg.job_name},
        },
        "spec": {
            "clusterIP": "None",
            "selector": {JOB_LABEL: cfg.job_name, "role": "worker"},
            "ports": [{"port": 8471, "name": "coordinator"}],
        },
    }
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {
            "name": name,
            "namespace": cfg.namespace,
            "labels": {JOB_LABEL: cfg.job_name},
        },
        "spec": {
            "serviceName": name,
            "replicas": replicas,
            "selector": {
                "matchLabels": {JOB_LABEL: cfg.job_name, "role": "worker"}
            },
            "template": {
                "metadata": {
                    "labels": {
                        JOB_LABEL: cfg.job_name,
                        "app": "elasticdl-tpu",
                        "role": "worker",
                    }
                },
                "spec": {
                    "nodeSelector": node_selector,
                    "containers": [
                        {
                            "name": "worker",
                            "image": cfg.image_name,
                            "imagePullPolicy": cfg.image_pull_policy,
                            "command": ["python", "-m", "elasticdl_tpu.worker.main"],
                            "args": args,
                            "resources": {"requests": resources, "limits": {
                                k: v for k, v in resources.items()
                                if k == "google.com/tpu"
                            }},
                            "env": _env_list(worker_cfg, extra_env),
                        }
                    ],
                },
            },
        },
    }
    return [headless, sts]


def render_worker_pod(
    cfg: JobConfig, worker_id: int, pod_name: str = "",
) -> Dict[str, Any]:
    """One master-managed worker pod (reference parity: the instance
    manager's create_worker — pods created/relaunched one by one by the
    master, unlike the StatefulSet flavor where k8s owns replacement). Used
    by master/k8s_instance_manager.py, which passes generation-suffixed
    `pod_name`s so relaunches are new pod objects; restartPolicy=Never
    because relaunch accounting lives in the manager's budget, not the
    kubelet."""
    master_svc = f"{cfg.job_name}-master"
    port = int(cfg.master_addr.rsplit(":", 1)[1]) if ":" in cfg.master_addr else DEFAULT_MASTER_PORT
    worker_cfg = cfg.replace(master_addr=f"{master_svc}:{port}")
    node_selector, resources, hosts = _tpu_scheduling(cfg)
    if hosts is not None and hosts > 1:
        # a multi-host slice is one SPMD cohort; managed pods have no cohort
        # addressing (see JobConfig.validate on instance_manager) — only the
        # StatefulSet flavor can host it
        raise ValueError(
            f"tpu_type={cfg.tpu_type} is a {hosts}-host slice and needs the "
            "StatefulSet worker flavor (instance_manager=''), not "
            "master-managed pods"
        )
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod_name or f"{cfg.job_name}-worker-{worker_id}",
            "namespace": cfg.namespace,
            "labels": {
                JOB_LABEL: cfg.job_name,
                "app": "elasticdl-tpu",
                "role": "worker",
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "nodeSelector": node_selector,
            "containers": [
                {
                    "name": "worker",
                    "image": cfg.image_name,
                    "imagePullPolicy": cfg.image_pull_policy,
                    "command": ["python", "-m", "elasticdl_tpu.worker.main"],
                    "args": worker_cfg.to_argv(),
                    "resources": {"requests": resources, "limits": {
                        k: v for k, v in resources.items()
                        if k == "google.com/tpu"
                    }},
                    "env": _env_list(
                        worker_cfg, {WorkerEnv.WORKER_ID: str(worker_id)}
                    ),
                }
            ],
        },
    }


def render_job_manifests(cfg: JobConfig) -> List[Dict[str, Any]]:
    """Two worker-deployment flavors: the default renders workers as a
    StatefulSet (k8s owns replacement; right for TPU slices provisioned as a
    unit); --instance_manager=k8s renders ONLY the master, which then
    creates/watches/relaunches worker pods itself through
    master/k8s_instance_manager.py (the reference's instance-manager shape —
    the flag rides to the master through the pod args via to_argv)."""
    manifests = [render_master_pod(cfg), render_master_service(cfg)]
    if cfg.instance_manager != "k8s":
        manifests += render_worker_statefulset(cfg)
    return manifests


def submit(cfg: JobConfig) -> int:
    manifests = render_job_manifests(cfg)
    doc = yaml.safe_dump_all(manifests, sort_keys=False)
    kubectl = shutil.which("kubectl")
    if kubectl is None:
        logger.warning("kubectl not found; printing manifests to stdout")
        sys.stdout.write(doc)
        return 0
    proc = subprocess.run(
        [kubectl, "-n", cfg.namespace, "apply", "-f", "-"],
        input=doc.encode(),
        capture_output=True,
    )
    sys.stdout.write(proc.stdout.decode())
    sys.stderr.write(proc.stderr.decode())
    return proc.returncode


def delete_job(cfg: JobConfig) -> int:
    kubectl = shutil.which("kubectl")
    if kubectl is None:
        logger.error("kubectl not found")
        return 1
    proc = subprocess.run(
        [
            kubectl, "-n", cfg.namespace, "delete",
            "pod,service,statefulset", "-l", f"{JOB_LABEL}={cfg.job_name}",
        ],
        capture_output=True,
    )
    sys.stdout.write(proc.stdout.decode())
    return proc.returncode
