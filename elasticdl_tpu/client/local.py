"""Local job launcher: master + worker processes on this host.

Reference parity: the reference's only launch path was Kubernetes
(elasticdl_client/api.py builds an image and submits a master pod). A local
process mode existed only inside tests; here it is a first-class launcher —
the same Master control plane and ProcessManager drive either subprocesses
(this module) or pods (client/k8s.py), so a job debugged locally submits to a
TPU slice unchanged.

Master crash-restart chaos (`--master_restarts`, ISSUE 5): when the
`master_crash` fault site fires its catchable `drop` flavor inside
Master.wait, this launcher crashes the master ABRUPTLY (no shutdown
handshake reaches the workers), rebuilds it on the same port, and rebinds
the process manager to the successor. The new master replays the
control-plane journal (master/journal.py), takes over under generation+1,
and the still-running workers reconnect through the generation handshake —
no worker process restarts, no lost task accounting.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.main import Master
from elasticdl_tpu.master.process_manager import ProcessManager

logger = default_logger(__name__)


from elasticdl_tpu.common.net import PortBindError, bind_with_retry, free_port  # noqa: F401  (re-export)


def _rebuild_master(cfg: JobConfig, attempts: int = 20) -> Master:
    """Construct the successor master on the SAME address the workers hold.
    The crashed server's port can linger for a beat after grpc stop, so a
    lost bind is retried briefly rather than failing the recovery."""
    last: Optional[Exception] = None
    for _ in range(attempts):
        try:
            return Master(cfg)
        except PortBindError as e:
            last = e
            # ONE local launcher waiting for its own crashed server's port
            # to free — no fleet to desynchronize: edl-lint: disable=EDL304
            time.sleep(0.25)
    raise RuntimeError(
        f"master restart could not rebind {cfg.master_addr}: {last}"
    )


def run_local(
    cfg: JobConfig,
    extra_env: Optional[Dict[str, str]] = None,
    log_dir: Optional[str] = None,
    timeout_s: Optional[float] = None,
) -> int:
    """Run a whole job on this host: in-process master, subprocess workers."""
    if cfg.master_addr.endswith(":0"):
        # bind_with_retry closes free_port()'s TOCTOU window: Master binds
        # its port during construction and raises PortBindError when the
        # pick was lost to a concurrent bind — retry with a fresh port
        # instead of failing the whole job submission
        def build(port: int) -> Master:
            return Master(cfg.replace(master_addr=f"localhost:{port}"))

        port, master = bind_with_retry(build)
        cfg = cfg.replace(master_addr=f"localhost:{port}")
    else:
        master = Master(cfg)
    manager = ProcessManager(
        cfg,
        membership=master.membership,
        extra_env=extra_env,
        log_dir=log_dir,
        job_finished_fn=master.dispatcher.finished,
        # planned resizes quiesce through the heartbeat should_checkpoint bit
        checkpoint_request_fn=lambda: master.servicer.request_checkpoint(0),
        journal=master.journal,
    )
    # Straggler-onset OFFENDER snapshot (the master's own hook already
    # dumps the MASTER's flight ring): only this launcher knows worker
    # pids, so the SIGUSR2 trigger that cuts the offender's black box is
    # wired here. Cohort member names carry their process index
    # (`...#p<i>`), so the signal lands on the one slow process.
    def _offender_flight_hook(info: dict) -> None:
        name = str(info.get("worker_name", ""))
        process_index = None
        if "#p" in name:
            try:
                process_index = int(name.rsplit("#p", 1)[1])
            except ValueError:
                process_index = None
        worker_id = int(info.get("worker_id", -1))
        if process_index is not None:
            # a cohort member: the proc table is keyed by process index
            # under the leader's logical worker
            manager.request_flight_dump(0, process_index=process_index)
        elif worker_id >= 0:
            manager.request_flight_dump(worker_id)

    master.health.add_hook(_offender_flight_hook)
    # Closed-loop autoscaler (--autoscale): the ACTION surface lives
    # here — only the launcher owns worker processes. EDL501 allowlists
    # exactly this wiring (plus the autoscaler module itself): every
    # other resize path must go through the policy so cooldown and
    # journaling cannot be bypassed.
    if master.autoscaler is not None:
        from elasticdl_tpu.master.autoscaler import ProcessManagerTarget

        autoscale_target = ProcessManagerTarget(
            manager, servicer=master.servicer,
            membership=master.membership,
        )
        master.autoscaler.bind_target(autoscale_target)
        # measured re-formation durations feed the cost model's EWMA —
        # the bench-seeded estimate converges to THIS deployment's real
        # recovery cost. The lambda reads the `master` LOCAL by
        # reference (reassigned on --master_restarts recovery), so a
        # successor's cost model keeps receiving observations; capturing
        # the autoscaler by value would feed the dead predecessor's EWMA
        # forever while the live gate ran on the static seed.
        manager.add_reform_observer(
            lambda seconds, old, new:
                master.autoscaler.cost.observe_recovery(seconds)
        )
    else:
        autoscale_target = None
    master.start()
    manager.start_workers()
    deadline = time.time() + timeout_s if timeout_s else None
    restarts_left = cfg.master_restarts
    try:
        while True:
            remaining = deadline - time.time() if deadline else None
            try:
                ok = master.wait(timeout_s=remaining, abort_fn=manager.all_failed)
                break
            except faults.FaultInjected as e:
                if e.site != "master_crash" or restarts_left <= 0:
                    raise
                restarts_left -= 1
                logger.warning(
                    "master crash injected (%s); restarting in place "
                    "(%d restart(s) left)", e, restarts_left,
                )
                master.crash()
                master = _rebuild_master(cfg)
                # the successor's health scorer needs the launcher hook
                # re-wired (Master.__init__ only adds its own master-side
                # dump hook)
                master.health.add_hook(_offender_flight_hook)
                manager.rebind_master(
                    master.membership,
                    master.dispatcher.finished,
                    lambda m=master: m.servicer.request_checkpoint(0),
                    journal=master.journal,
                )
                if master.autoscaler is not None and autoscale_target:
                    # the successor's policy engine replayed its cooldown/
                    # budget state from the journal; rebind the action
                    # surface (manager survives, servicer/membership
                    # moved). The reform observer needs no re-pointing —
                    # it closes over this function's `master`, which was
                    # just reassigned to the successor.
                    autoscale_target.rebind(
                        servicer=master.servicer,
                        membership=master.membership,
                    )
                    master.autoscaler.bind_target(autoscale_target)
                master.start()
    finally:
        # final fleet rollup before teardown (ClusterHealth.update never
        # raises): a local run surfaces "was any worker dragging" without
        # anyone having scraped /metrics during the job
        rollup = master.health.update()
        if rollup.get("workers_reporting"):
            logger.info(
                "final cluster health: %d/%d worker(s) reporting, "
                "step-time skew %.2f, %d straggler(s)",
                rollup["workers_reporting"], rollup.get("workers_alive", 0),
                rollup.get("skew", 1.0), rollup["straggler_count"],
            )
        master.shutdown()
        manager.stop()
    return 0 if ok else 1
