"""Local job launcher: master + worker processes on this host.

Reference parity: the reference's only launch path was Kubernetes
(elasticdl_client/api.py builds an image and submits a master pod). A local
process mode existed only inside tests; here it is a first-class launcher —
the same Master control plane and ProcessManager drive either subprocesses
(this module) or pods (client/k8s.py), so a job debugged locally submits to a
TPU slice unchanged.
"""

from __future__ import annotations

from typing import Dict, Optional

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.main import Master
from elasticdl_tpu.master.process_manager import ProcessManager

logger = default_logger(__name__)


from elasticdl_tpu.common.net import bind_with_retry, free_port  # noqa: F401  (re-export)


def run_local(
    cfg: JobConfig,
    extra_env: Optional[Dict[str, str]] = None,
    log_dir: Optional[str] = None,
    timeout_s: Optional[float] = None,
) -> int:
    """Run a whole job on this host: in-process master, subprocess workers."""
    if cfg.master_addr.endswith(":0"):
        # bind_with_retry closes free_port()'s TOCTOU window: Master binds
        # its port during construction and raises PortBindError when the
        # pick was lost to a concurrent bind — retry with a fresh port
        # instead of failing the whole job submission
        def build(port: int) -> Master:
            return Master(cfg.replace(master_addr=f"localhost:{port}"))

        port, master = bind_with_retry(build)
        cfg = cfg.replace(master_addr=f"localhost:{port}")
    else:
        master = Master(cfg)
    manager = ProcessManager(
        cfg,
        membership=master.membership,
        extra_env=extra_env,
        log_dir=log_dir,
        job_finished_fn=master.dispatcher.finished,
        # planned resizes quiesce through the heartbeat should_checkpoint bit
        checkpoint_request_fn=lambda: master.servicer.request_checkpoint(0),
    )
    master.start()
    manager.start_workers()
    try:
        ok = master.wait(timeout_s=timeout_s, abort_fn=manager.all_failed)
    finally:
        master.shutdown()
        manager.stop()
    return 0 if ok else 1
