"""`elasticdl-tpu` CLI entrypoint.

Reference parity: elasticdl_client/main.py — verbs `train`, `evaluate`,
`predict`, `zoo init/build/push`. This module currently exposes the verb
surface and local-mode dispatch; Kubernetes submission lands with the
cluster client (see elasticdl_tpu/client/k8s.py when present).
"""

from __future__ import annotations

import sys
from typing import List, Optional

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.version import __version__

VERBS = ("train", "evaluate", "predict", "zoo", "version")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print(f"usage: elasticdl-tpu {{{'|'.join(VERBS)}}} [flags]")
        return 0
    verb, rest = argv[0], argv[1:]
    if verb == "version":
        print(__version__)
        return 0
    if verb not in VERBS:
        print(f"unknown verb {verb!r}; expected one of {VERBS}", file=sys.stderr)
        return 2
    # Deferred import: the launcher pulls in jax; keep `--help` cheap.
    from elasticdl_tpu.client import api

    if verb == "zoo":
        return api.zoo(rest)
    cfg = JobConfig.from_argv(rest)
    if verb == "train":
        return api.train(cfg)
    if verb == "evaluate":
        return api.evaluate(cfg)
    if verb == "predict":
        return api.predict(cfg)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
