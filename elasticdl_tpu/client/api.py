"""Client verb implementations.

Reference parity: elasticdl_client/api.py — train/evaluate/predict submit a
job; zoo manages the model-zoo artifact. Two launch targets:
- local (default when no --image_name): master in-process + subprocess
  workers on this host;
- k8s: render a master pod manifest for a TPU slice (client/k8s.py) and
  submit it with kubectl.
"""

from __future__ import annotations

from typing import List

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


def _launch(cfg: JobConfig) -> int:
    cfg.validate()
    if cfg.image_name:
        from elasticdl_tpu.client import k8s

        return k8s.submit(cfg)
    from elasticdl_tpu.client.local import run_local

    return run_local(cfg)


def _require_data(cfg: JobConfig, field: str, verb: str) -> None:
    """Verb-specific data-flag validation (round-3, VERDICT #8): a missing
    data path used to surface deep in the master as an opaque reader error;
    fail at the verb boundary with the flag name instead."""
    if not getattr(cfg, field):
        raise ValueError(f"`{verb}` requires --{field}")


def train(cfg: JobConfig) -> int:
    _require_data(cfg, "training_data", "train")
    return _launch(cfg)


def evaluate(cfg: JobConfig) -> int:
    _require_data(cfg, "validation_data", "evaluate")
    return _launch(cfg.replace(job_type=JobType.EVALUATION_ONLY))


def predict(cfg: JobConfig) -> int:
    _require_data(cfg, "prediction_data", "predict")
    return _launch(cfg.replace(job_type=JobType.PREDICTION_ONLY))


def zoo(argv: List[str]) -> int:
    """zoo init/build/push — model-zoo image management."""
    from elasticdl_tpu.client import zoo as zoo_mod

    return zoo_mod.main(argv)
