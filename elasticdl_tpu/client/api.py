"""Client verb implementations.

Reference parity: elasticdl_client/api.py (train/evaluate/predict submit a
master pod; zoo manages the model-zoo image). Local mode runs master+workers
as processes on this host; k8s mode renders manifests for a TPU slice.
"""

from __future__ import annotations

import sys
from typing import List

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import JobType


def _not_ready(what: str) -> int:
    print(
        f"{what}: the master/worker runtime is not wired into the CLI yet "
        "(see elasticdl_tpu/master, elasticdl_tpu/worker).",
        file=sys.stderr,
    )
    return 3


def train(cfg: JobConfig) -> int:
    cfg.validate()
    return _not_ready("train")


def evaluate(cfg: JobConfig) -> int:
    cfg = cfg.replace(job_type=JobType.EVALUATION_ONLY)
    cfg.validate()
    return _not_ready("evaluate")


def predict(cfg: JobConfig) -> int:
    cfg = cfg.replace(job_type=JobType.PREDICTION_ONLY)
    cfg.validate()
    return _not_ready("predict")


def zoo(argv: List[str]) -> int:
    return _not_ready("zoo")
