"""`zoo` verbs: scaffold, build and push the model-zoo image.

Reference parity: elasticdl_client `zoo init/build/push` — the model zoo is a
directory of model modules baked into a Docker image the job pods run.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
from typing import List

from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)

_DOCKERFILE = """\
FROM {base_image}
COPY . /model_zoo
ENV PYTHONPATH=/model_zoo:$PYTHONPATH
"""

_TEMPLATE_MODEL = '''\
"""Model-zoo template. Contract: custom_model/loss/optimizer/dataset_fn/
eval_metrics_fn module-level functions (see model_zoo/mnist/mnist_cnn.py
for a complete example)."""

import flax.linen as nn
import jax.numpy as jnp
import optax

from elasticdl_tpu.training import metrics as metrics_lib


class MyModel(nn.Module):
    @nn.compact
    def __call__(self, x, training: bool = False):
        return nn.Dense(2)(x)


def custom_model(**kwargs):
    return MyModel()


def loss(labels, outputs):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, jnp.asarray(labels, jnp.int32).reshape(-1)
    )


def optimizer(**kwargs):
    return optax.adam(float(kwargs.get("learning_rate", 1e-3)))


def dataset_fn(mode, metadata):
    raise NotImplementedError


def eval_metrics_fn():
    return {"accuracy": metrics_lib.Accuracy()}
'''


def init(model_zoo_dir: str) -> int:
    os.makedirs(model_zoo_dir, exist_ok=True)
    template = os.path.join(model_zoo_dir, "my_model.py")
    if not os.path.exists(template):
        with open(template, "w") as f:
            f.write(_TEMPLATE_MODEL)
    docker = os.path.join(model_zoo_dir, "Dockerfile")
    if not os.path.exists(docker):
        with open(docker, "w") as f:
            f.write(_DOCKERFILE.format(base_image="python:3.12-slim"))
    logger.info("initialized model zoo at %s", model_zoo_dir)
    return 0


def build(model_zoo_dir: str, image: str, base_image: str) -> int:
    docker = shutil.which("docker")
    dockerfile = os.path.join(model_zoo_dir, "Dockerfile")
    if not os.path.exists(dockerfile):
        with open(dockerfile, "w") as f:
            f.write(_DOCKERFILE.format(base_image=base_image))
    if docker is None:
        logger.error("docker not found; wrote %s — build it where docker runs", dockerfile)
        return 1
    return subprocess.call([docker, "build", "-t", image, model_zoo_dir])


def push(image: str) -> int:
    docker = shutil.which("docker")
    if docker is None:
        logger.error("docker not found")
        return 1
    return subprocess.call([docker, "push", image])


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser("elasticdl-tpu zoo")
    sub = parser.add_subparsers(dest="verb", required=True)
    p_init = sub.add_parser("init")
    p_init.add_argument("--model_zoo", default="model_zoo")
    p_build = sub.add_parser("build")
    p_build.add_argument("--model_zoo", default="model_zoo")
    p_build.add_argument("--image", required=True)
    p_build.add_argument("--base_image", default="python:3.12-slim")
    p_push = sub.add_parser("push")
    p_push.add_argument("--image", required=True)
    ns = parser.parse_args(argv)
    if ns.verb == "init":
        return init(ns.model_zoo)
    if ns.verb == "build":
        return build(ns.model_zoo, ns.image, ns.base_image)
    if ns.verb == "push":
        return push(ns.image)
    return 2


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
