"""elasticdl_tpu — a TPU-native elastic distributed training framework.

Re-designed from scratch with the capabilities of ElasticDL
(reference: william-wang/elasticdl, upstream sql-machine-learning/elasticdl):

- dynamic data sharding via a master-hosted task queue (control plane kept,
  reference: elasticdl/python/master/task_dispatcher.py),
- elastic worker membership with mesh re-formation instead of Horovod
  re-rendezvous (reference: elasticdl/python/master/rendezvous_server.py),
- model state in device HBM, sharded by a `jax.sharding.Mesh`, instead of a
  parameter-server tier (reference: elasticdl/pkg/ps/*.go),
- the train step as a single `jax.jit`-compiled XLA program with `optax`
  optimizers instead of TF2-eager + server-side optimizer application
  (reference: elasticdl/python/worker/worker.py).
"""

from elasticdl_tpu.version import __version__

__all__ = ["__version__"]
