// Vectorized host-side batch parsers for the input pipeline.
//
// Reference parity: the reference's input path leaned on tf.data's C++ op
// kernels to keep record decoding off the Python interpreter (SURVEY §2.4,
// §7 hard-part 4). This is the rebuild's equivalent: one ctypes call parses
// an entire batch of records into preallocated numpy buffers, releasing the
// GIL for the duration (ctypes drops it around foreign calls), so a thread
// pool of parsers scales across cores instead of serializing on the
// interpreter the way the per-record Python loop did.
//
// Layout contract (shared with data/parsing.py): the caller concatenates the
// batch's records into one contiguous buffer and passes n+1 offsets;
// record i is buf[offsets[i], offsets[i+1]). Records may keep a trailing
// newline — parsers treat '\n' as end-of-record.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC batch_parse.cc -o libbatch_parse.so
// (data/nativelib.py auto-builds exactly this name — lib<stem>.so — on
// first use; a manually built .so must match it or the loader ignores it).

#include <cstdint>
#include <cstring>

namespace {

// Parse a non-negative decimal int from [p, end); stops at the first
// non-digit. Returns the value and advances *pp. Criteo dense fields can be
// negative in the wild (counts occasionally are), so allow a leading '-'.
inline long long parse_int(const char** pp, const char* end) {
  const char* p = *pp;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  long long v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + (*p - '0');
    ++p;
  }
  *pp = p;
  return neg ? -v : v;
}

// Parse a float: integer part, optional fraction, optional exponent — the
// same grammar Python's float() accepts for finite decimals, so the native
// kernel and the pure-Python fallback parse identical values ("2.5e2" must
// be 250, not 2.5). Criteo dense fields are integers in practice, but the
// reference's CSV path tolerated floats. "" parses as 0.
inline float parse_float(const char** pp, const char* end) {
  const char* p = *pp;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  double v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + (*p - '0');
    ++p;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      v += (*p - '0') * scale;
      scale *= 0.1;
      ++p;
    }
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    const char* mark = p;  // only consume a well-formed exponent
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) {
      eneg = (*p == '-');
      ++p;
    }
    if (p < end && *p >= '0' && *p <= '9') {
      long long e = 0;
      while (p < end && *p >= '0' && *p <= '9') {
        e = e * 10 + (*p - '0');
        ++p;
      }
      double f = 1.0;
      for (long long i = 0; i < e && i < 64; ++i) f *= 10.0;
      v = eneg ? v / f : v * f;
    } else {
      p = mark;  // bare 'e' is not an exponent; leave it for skip_field
    }
  }
  *pp = p;
  return static_cast<float>(neg ? -v : v);
}

// Parse a lowercase/uppercase hex field (Criteo categorical), masked to
// int32 range like the Python parser's `int(p, 16) & 0x7FFFFFFF`.
inline int32_t parse_hex(const char** pp, const char* end) {
  const char* p = *pp;
  uint64_t v = 0;
  while (p < end) {
    char c = *p;
    uint32_t d;
    if (c >= '0' && c <= '9') d = c - '0';
    else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
    else break;
    v = (v << 4) | d;
    ++p;
  }
  *pp = p;
  return static_cast<int32_t>(v & 0x7FFFFFFF);
}

inline void skip_field(const char** pp, const char* end, char sep) {
  const char* p = *pp;
  while (p < end && *p != sep && *p != '\n') ++p;
  *pp = p;
}

}  // namespace

extern "C" {

// Criteo TSV: label \t d1..d13 \t c1..c26(hex). Missing fields parse as 0
// (empty string between tabs), short records are zero-padded — matching the
// Python parser in model_zoo/deepfm/deepfm.py exactly.
//
// labels: int32[n]; dense: float32[n*num_dense]; cat: int32[n*num_cat].
// Returns 0 on success (this parser never fails: malformed bytes degrade to
// zeros, same as the Python twin's `errors="replace"` stance).
int edl_parse_criteo(const char* buf, const int64_t* offsets, int64_t n,
                     int num_dense, int num_cat, int32_t* labels,
                     float* dense, int32_t* cat) {
  for (int64_t i = 0; i < n; ++i) {
    const char* p = buf + offsets[i];
    const char* end = buf + offsets[i + 1];
    while (end > p && (end[-1] == '\n' || end[-1] == '\r')) --end;

    labels[i] = static_cast<int32_t>(parse_int(&p, end));
    skip_field(&p, end, '\t');

    float* drow = dense + i * num_dense;
    for (int f = 0; f < num_dense; ++f) {
      drow[f] = 0.0f;
      if (p < end && *p == '\t') {
        ++p;
        drow[f] = parse_float(&p, end);
        skip_field(&p, end, '\t');
      }
    }
    int32_t* crow = cat + i * num_cat;
    for (int f = 0; f < num_cat; ++f) {
      crow[f] = 0;
      if (p < end && *p == '\t') {
        ++p;
        crow[f] = parse_hex(&p, end);
        skip_field(&p, end, '\t');
      }
    }
  }
  return 0;
}

// Delimiter-separated numeric table (CSV/TSV of floats): parses `num_cols`
// float fields per record into out[n, num_cols]; `label_col` (if >= 0) is
// copied to labels as int32 and excluded from out when exclude_label != 0.
// Used by CSV-style tabular configs to skip per-field Python parsing.
int edl_parse_numeric(const char* buf, const int64_t* offsets, int64_t n,
                      char sep, int num_cols, int label_col,
                      int exclude_label, int32_t* labels, float* out) {
  int out_cols = num_cols - (exclude_label && label_col >= 0 ? 1 : 0);
  for (int64_t i = 0; i < n; ++i) {
    const char* p = buf + offsets[i];
    const char* end = buf + offsets[i + 1];
    while (end > p && (end[-1] == '\n' || end[-1] == '\r')) --end;
    float* row = out + i * out_cols;
    int oc = 0;
    for (int c = 0; c < num_cols; ++c) {
      float v = parse_float(&p, end);
      if (c == label_col) {
        if (labels) labels[i] = static_cast<int32_t>(v);
        if (!exclude_label) row[oc++] = v;
      } else {
        row[oc++] = v;
      }
      skip_field(&p, end, sep);
      if (p < end && *p == sep) ++p;
    }
  }
  return 0;
}

// Fixed-width binary records (the synthetic mnist/cifar layout: 1 label byte
// + w uint8 payload): fan out to labels int32[n] and float32[n*w] scaled by
// `scale` (e.g. 1/255). Avoids n numpy frombuffer calls.
int edl_parse_u8_image(const char* buf, const int64_t* offsets, int64_t n,
                       int width, float scale, int32_t* labels, float* out) {
  for (int64_t i = 0; i < n; ++i) {
    const unsigned char* p =
        reinterpret_cast<const unsigned char*>(buf + offsets[i]);
    int64_t len = offsets[i + 1] - offsets[i];
    if (len < 1 + width) return -1;
    labels[i] = p[0];
    float* row = out + i * static_cast<int64_t>(width);
    const unsigned char* px = p + 1;
    for (int j = 0; j < width; ++j) row[j] = px[j] * scale;
  }
  return 0;
}

}  // extern "C"
