// EDLR: a chunked, indexed, checksummed record file format + C API.
//
// Reference parity: the reference's training data lives in RecordIO files
// read through the external C++ `pyrecordio` library, whose (file, offset,
// count) spans define tasks (SURVEY §2.4, §2.7 item 3). This is a fresh
// format and implementation with the same role: sharded binary records,
// O(1) seek to any record index via a trailing chunk index, per-chunk CRC.
//
// Layout (all integers little-endian):
//   file   := "EDLR" u32(version=1) chunk* index footer
//   chunk  := "CHNK" u32(num_records) u64(payload_len) u32(crc32(payload))
//             payload
//   payload:= { u32(record_len) bytes }*
//   index  := "INDX" u32(num_chunks) { u64(chunk_off) u64(first_record) }*
//   footer := u64(index_off) "EDLR"
//
// Build: g++ -O2 -shared -fPIC recordio.cc -o libedlrecordio.so
// (no external deps; crc32 implemented inline).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr char kFileMagic[4] = {'E', 'D', 'L', 'R'};
constexpr char kChunkMagic[4] = {'C', 'H', 'N', 'K'};
constexpr char kIndexMagic[4] = {'I', 'N', 'D', 'X'};
constexpr uint32_t kVersion = 1;

uint32_t crc32_table[256];
bool crc32_init_done = false;

void crc32_init() {
  if (crc32_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc32_init_done = true;
}

uint32_t crc32(const uint8_t* data, size_t n) {
  crc32_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = crc32_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct ChunkIndexEntry {
  uint64_t offset;        // file offset of the chunk header
  uint64_t first_record;  // global index of the chunk's first record
};

struct Reader {
  FILE* f = nullptr;
  std::vector<ChunkIndexEntry> index;
  uint64_t num_records = 0;
  std::string error;
  // chunk cache
  int64_t cached_chunk = -1;
  std::vector<uint8_t> payload;
  std::vector<std::pair<uint32_t, uint32_t>> record_spans;  // (off, len)
  // read() output buffer
  std::vector<uint8_t> out;
};

struct Writer {
  FILE* f = nullptr;
  std::vector<ChunkIndexEntry> index;
  std::vector<uint8_t> payload;
  uint32_t chunk_records = 0;
  uint64_t total_records = 0;
  uint64_t chunk_target_bytes = 1 << 20;
  std::string error;
};

template <typename T>
bool read_pod(FILE* f, T* v) {
  return fread(v, sizeof(T), 1, f) == 1;
}

template <typename T>
bool write_pod(FILE* f, const T& v) {
  return fwrite(&v, sizeof(T), 1, f) == 1;
}

bool load_chunk(Reader* r, size_t chunk_i) {
  if ((int64_t)chunk_i == r->cached_chunk) return true;
  // The payload buffer is overwritten below; until the new chunk fully
  // validates, the cache must not claim to hold any chunk, or a failed load
  // would leave a stale cache serving the wrong bytes on a later fast-path hit.
  r->cached_chunk = -1;
  const ChunkIndexEntry& e = r->index[chunk_i];
  if (fseek(r->f, (long)e.offset, SEEK_SET) != 0) {
    r->error = "seek failed";
    return false;
  }
  char magic[4];
  uint32_t num_records, crc;
  uint64_t payload_len;
  if (fread(magic, 4, 1, r->f) != 1 || memcmp(magic, kChunkMagic, 4) != 0) {
    r->error = "bad chunk magic";
    return false;
  }
  if (!read_pod(r->f, &num_records) || !read_pod(r->f, &payload_len) ||
      !read_pod(r->f, &crc)) {
    r->error = "truncated chunk header";
    return false;
  }
  r->payload.resize(payload_len);
  if (payload_len && fread(r->payload.data(), 1, payload_len, r->f) != payload_len) {
    r->error = "truncated chunk payload";
    return false;
  }
  if (crc32(r->payload.data(), payload_len) != crc) {
    r->error = "chunk crc mismatch";
    return false;
  }
  r->record_spans.clear();
  r->record_spans.reserve(num_records);
  size_t off = 0;
  for (uint32_t i = 0; i < num_records; ++i) {
    if (off + 4 > payload_len) {
      r->error = "corrupt record framing";
      return false;
    }
    uint32_t len;
    memcpy(&len, r->payload.data() + off, 4);
    off += 4;
    if (off + len > payload_len) {
      r->error = "corrupt record length";
      return false;
    }
    r->record_spans.emplace_back((uint32_t)off, len);
    off += len;
  }
  r->cached_chunk = (int64_t)chunk_i;
  return true;
}

}  // namespace

extern "C" {

// ------------------------------ reader ------------------------------ //

void* edlr_reader_open(const char* path) {
  Reader* r = new Reader();
  r->f = fopen(path, "rb");
  if (!r->f) {
    delete r;
    return nullptr;
  }
  char magic[4];
  uint32_t version;
  if (fread(magic, 4, 1, r->f) != 1 || memcmp(magic, kFileMagic, 4) != 0 ||
      !read_pod(r->f, &version) || version != kVersion) {
    fclose(r->f);
    delete r;
    return nullptr;
  }
  // footer: last 12 bytes = u64 index_off + magic
  if (fseek(r->f, -12, SEEK_END) != 0) {
    fclose(r->f);
    delete r;
    return nullptr;
  }
  uint64_t index_off;
  char tail[4];
  if (!read_pod(r->f, &index_off) || fread(tail, 4, 1, r->f) != 1 ||
      memcmp(tail, kFileMagic, 4) != 0 ||
      fseek(r->f, (long)index_off, SEEK_SET) != 0) {
    fclose(r->f);
    delete r;
    return nullptr;
  }
  char imagic[4];
  uint32_t num_chunks;
  if (fread(imagic, 4, 1, r->f) != 1 || memcmp(imagic, kIndexMagic, 4) != 0 ||
      !read_pod(r->f, &num_chunks)) {
    fclose(r->f);
    delete r;
    return nullptr;
  }
  r->index.resize(num_chunks);
  for (uint32_t i = 0; i < num_chunks; ++i) {
    if (!read_pod(r->f, &r->index[i].offset) ||
        !read_pod(r->f, &r->index[i].first_record)) {
      fclose(r->f);
      delete r;
      return nullptr;
    }
  }
  // total records = first_record of a virtual end chunk: read last chunk hdr
  if (num_chunks == 0) {
    r->num_records = 0;
  } else {
    const ChunkIndexEntry& last = r->index.back();
    if (fseek(r->f, (long)(last.offset + 4), SEEK_SET) != 0) {
      fclose(r->f);
      delete r;
      return nullptr;
    }
    uint32_t n;
    if (!read_pod(r->f, &n)) {
      fclose(r->f);
      delete r;
      return nullptr;
    }
    r->num_records = last.first_record + n;
  }
  return r;
}

long long edlr_reader_num_records(void* h) {
  return h ? (long long)((Reader*)h)->num_records : -1;
}

// Packs records [start, end) as {u32 len, bytes}* into an internal buffer.
// Returns byte size, or -1 on error. Buffer valid until the next call.
// Out-of-range spans clamp to the file (matching the Python twin), they are
// not errors.
long long edlr_reader_read(void* h, long long start, long long end) {
  Reader* r = (Reader*)h;
  if (!r) return -1;
  if (start < 0) start = 0;
  if (end < 0) end = 0;
  if ((uint64_t)end > r->num_records) end = (long long)r->num_records;
  r->out.clear();
  if (start >= end) return 0;
  // binary search the chunk containing `start`
  size_t lo = 0, hi = r->index.size();
  while (lo + 1 < hi) {
    size_t mid = (lo + hi) / 2;
    if (r->index[mid].first_record <= (uint64_t)start) lo = mid;
    else hi = mid;
  }
  for (size_t ci = lo; ci < r->index.size(); ++ci) {
    if (r->index[ci].first_record >= (uint64_t)end) break;
    if (!load_chunk(r, ci)) return -1;
    uint64_t base = r->index[ci].first_record;
    for (size_t k = 0; k < r->record_spans.size(); ++k) {
      uint64_t gid = base + k;
      if (gid < (uint64_t)start) continue;
      if (gid >= (uint64_t)end) break;
      uint32_t off = r->record_spans[k].first, len = r->record_spans[k].second;
      size_t pos = r->out.size();
      r->out.resize(pos + 4 + len);
      memcpy(r->out.data() + pos, &len, 4);
      memcpy(r->out.data() + pos + 4, r->payload.data() + off, len);
    }
  }
  return (long long)r->out.size();
}

const uint8_t* edlr_reader_buffer(void* h) {
  return h ? ((Reader*)h)->out.data() : nullptr;
}

const char* edlr_reader_error(void* h) {
  return h ? ((Reader*)h)->error.c_str() : "null handle";
}

void edlr_reader_close(void* h) {
  if (!h) return;
  Reader* r = (Reader*)h;
  if (r->f) fclose(r->f);
  delete r;
}

// ------------------------------ writer ------------------------------ //

static bool flush_chunk(Writer* w) {
  if (w->chunk_records == 0) return true;
  ChunkIndexEntry e;
  e.offset = (uint64_t)ftell(w->f);
  e.first_record = w->total_records - w->chunk_records;
  uint32_t crc = crc32(w->payload.data(), w->payload.size());
  uint64_t payload_len = w->payload.size();
  if (fwrite(kChunkMagic, 4, 1, w->f) != 1 || !write_pod(w->f, w->chunk_records) ||
      !write_pod(w->f, payload_len) || !write_pod(w->f, crc) ||
      (payload_len &&
       fwrite(w->payload.data(), 1, payload_len, w->f) != payload_len)) {
    w->error = "chunk write failed";
    return false;
  }
  w->index.push_back(e);
  w->payload.clear();
  w->chunk_records = 0;
  return true;
}

void* edlr_writer_open(const char* path, long long chunk_bytes) {
  Writer* w = new Writer();
  w->f = fopen(path, "wb");
  if (!w->f) {
    delete w;
    return nullptr;
  }
  if (chunk_bytes > 0) w->chunk_target_bytes = (uint64_t)chunk_bytes;
  if (fwrite(kFileMagic, 4, 1, w->f) != 1 || !write_pod(w->f, kVersion)) {
    fclose(w->f);
    delete w;
    return nullptr;
  }
  return w;
}

int edlr_writer_write(void* h, const uint8_t* data, long long len) {
  Writer* w = (Writer*)h;
  if (!w || len < 0 || (unsigned long long)len > UINT32_MAX) return -1;
  uint32_t len32 = (uint32_t)len;
  size_t pos = w->payload.size();
  w->payload.resize(pos + 4 + len32);
  memcpy(w->payload.data() + pos, &len32, 4);
  if (len32) memcpy(w->payload.data() + pos + 4, data, len32);
  w->chunk_records++;
  w->total_records++;
  if (w->payload.size() >= w->chunk_target_bytes) {
    if (!flush_chunk(w)) return -1;
  }
  return 0;
}

long long edlr_writer_close(void* h) {
  Writer* w = (Writer*)h;
  if (!w) return -1;
  long long total = -1;
  if (flush_chunk(w)) {
    uint64_t index_off = (uint64_t)ftell(w->f);
    uint32_t num_chunks = (uint32_t)w->index.size();
    bool ok = fwrite(kIndexMagic, 4, 1, w->f) == 1 && write_pod(w->f, num_chunks);
    for (size_t i = 0; ok && i < w->index.size(); ++i) {
      ok = write_pod(w->f, w->index[i].offset) &&
           write_pod(w->f, w->index[i].first_record);
    }
    ok = ok && write_pod(w->f, index_off) && fwrite(kFileMagic, 4, 1, w->f) == 1;
    if (ok) total = (long long)w->total_records;
  }
  fclose(w->f);
  delete w;
  return total;
}

}  // extern "C"
